"""Native (C++) batch-gather fast path for DataLoader.

Reference analog: the C++ data plane (fluid/framework/data_feed.cc, the
DataLoader's C++ worker pool) — the reference feeds training from native
threads, not Python. Here `NativeArrayLoader` drives the pthread gather engine
in core/native/dataloader.cc over contiguous host arrays: workers assemble
batch buffers ahead of consumption (bounded by `depth`). Each delivered batch
is one native gather into the engine slot plus one memcpy out (the consumer
owns its batches across steps, so the slot can be recycled immediately); the
Python-side fancy-indexing and per-sample collate of the mp path are gone.

Used automatically by DataLoader for TensorDataset/array datasets with
num_workers > 0 and the default collate (engine="auto"), with the Python
multiprocessing path as fallback when the toolchain is unavailable.
"""
from __future__ import annotations

import ctypes
import threading

import numpy as np

_lib = None
_lib_tried = False


def _load():
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    from ..core.native.build import load
    lib = load("pt_dataloader", "dataloader.cc")
    if lib is None:
        return None
    lib.pt_dl_create.restype = ctypes.c_void_p
    lib.pt_dl_create.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                 ctypes.c_int64, ctypes.c_int, ctypes.c_int]
    lib.pt_dl_submit.restype = ctypes.c_int
    lib.pt_dl_submit.argtypes = [ctypes.c_void_p,
                                 ctypes.POINTER(ctypes.c_int64),
                                 ctypes.c_int64]
    lib.pt_dl_acquire.restype = ctypes.c_int64
    lib.pt_dl_acquire.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_void_p)]
    lib.pt_dl_release.argtypes = [ctypes.c_void_p]
    lib.pt_dl_close.argtypes = [ctypes.c_void_p]
    lib.pt_dl_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


class _Engine:
    """One gather engine over one contiguous array ([N, ...] row-major)."""

    def __init__(self, array: np.ndarray, n_threads: int, depth: int):
        lib = _load()
        if lib is None:
            raise RuntimeError("native dataloader unavailable")
        self._lib = lib
        self._arr = np.ascontiguousarray(array)   # keep alive: C++ reads it
        self._row_shape = self._arr.shape[1:]
        self._row_bytes = int(self._arr.dtype.itemsize *
                              int(np.prod(self._row_shape, dtype=np.int64)))
        self._h = lib.pt_dl_create(
            self._arr.ctypes.data_as(ctypes.c_void_p),
            self._arr.shape[0], self._row_bytes, n_threads, depth)
        if not self._h:
            raise RuntimeError("pt_dl_create failed")

    def submit(self, indices: np.ndarray) -> None:
        idx = np.ascontiguousarray(indices, dtype=np.int64)
        rc = self._lib.pt_dl_submit(
            self._h, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idx))
        if rc != 0:
            raise RuntimeError("pt_dl_submit failed (closed or bad index)")

    def acquire(self):
        """-> np view [n, *row_shape] valid until the next acquire, or None."""
        ptr = ctypes.c_void_p()
        n = self._lib.pt_dl_acquire(self._h, ctypes.byref(ptr))
        if n < 0:
            return None
        nbytes = int(n) * self._row_bytes
        raw = (ctypes.c_uint8 * nbytes).from_address(ptr.value)
        view = np.frombuffer(raw, dtype=self._arr.dtype)
        return view.reshape((int(n),) + self._row_shape)

    def close(self):
        self._lib.pt_dl_close(self._h)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.pt_dl_destroy(h)
            self._h = None


class NativeArrayLoader:
    """Iterate (batches of) one or more parallel arrays in native threads.

    arrays: list of [N, ...] numpy arrays sharing N (the TensorDataset
    layout). index_batches: iterable of per-batch row-index lists. Yields
    tuples of OWNED numpy arrays (copied out of the engine slot, so the
    consumer may hold them across steps)."""

    def __init__(self, arrays, index_batches, num_threads=4, depth=4):
        self._arrays = [np.asarray(a) for a in arrays]
        n = self._arrays[0].shape[0]
        for a in self._arrays:
            if a.shape[0] != n:
                raise ValueError("parallel arrays must share dim 0")
        self._batches = index_batches
        self._threads = max(1, num_threads)
        self._depth = max(1, depth)

    def __iter__(self):
        # the thread budget is TOTAL, split across the per-array engines with
        # the remainder distributed; each engine needs >= 1 thread, so more
        # arrays than budget means a mild oversubscription by design
        k = len(self._arrays)
        base, rem = divmod(self._threads, k)
        engines = [_Engine(a, max(1, base + (1 if i < rem else 0)),
                           self._depth)
                   for i, a in enumerate(self._arrays)]
        err = []

        def feed():
            try:
                for batch in self._batches:
                    for e in engines:
                        e.submit(np.asarray(batch))
            except Exception as ex:  # surfaced on the consumer side
                err.append(ex)
            finally:
                for e in engines:
                    e.close()

        feeder = threading.Thread(target=feed, daemon=True)
        feeder.start()
        try:
            while True:
                views = [e.acquire() for e in engines]
                if any(v is None for v in views):
                    break
                yield tuple(v.copy() for v in views)
            if err:
                raise err[0]
        finally:
            feeder.join(timeout=5)
            del engines
