"""paddle.io — datasets, samplers, DataLoader (reference: python/paddle/io/).

DataLoader supports num_workers>0 via multiprocessing (reference: io/dataloader/
dataloader_iter.py _worker_loop) with prefetching; batches land as Tensors on the
default device (host→HBM transfer overlapped by JAX's async dispatch).
"""
from __future__ import annotations

import itertools
import math
import multiprocessing as mp
import queue as queue_mod
import threading

import numpy as np

from ..core.tensor import Tensor
from ..core.rng import default_generator


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (list, tuple)) else [sample])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        import bisect
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[di - 1] if di > 0 else 0
        return self.datasets[di][idx - prev]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset, self.indices = dataset, list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    n = len(dataset)
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths) and abs(sum(lengths) - 1.0) < 1e-6:
        sizes = [int(math.floor(n * l)) for l in lengths]
        for i in range(n - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    rng = np.random.RandomState(generator.initial_seed() if generator else None)
    perm = rng.permutation(n)
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


# ---- samplers ----------------------------------------------------------------
def _epoch_seed(generator):
    """Fresh seed per epoch: advance the Generator's key stream (a fixed
    initial_seed would repeat the identical permutation every epoch)."""
    if generator is None:
        return int(np.random.randint(0, 2 ** 31 - 1))
    key = np.asarray(generator.next_key())
    return int(np.uint32(key[-1]))


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples if self._num_samples is not None else len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState(_epoch_seed(self.generator))
        if self.replacement:
            yield from rng.randint(0, n, self.num_samples).tolist()
        else:
            yield from rng.permutation(n)[:self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1,
                 drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Shards the sample space across data-parallel ranks (reference:
    io/dataloader/batch_sampler.py DistributedBatchSampler)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None, shuffle=False,
                 drop_last=False):
        from ..distributed import get_world_size, get_rank
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate([indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank::self.nranks].tolist()
        batch = []
        for idx in indices:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def set_epoch(self, epoch):
        self.epoch = epoch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size


# ---- collate -----------------------------------------------------------------
class BucketCollate:
    """Pad variable-length token sequences to power-of-two length BUCKETS so a
    compiled train step (jit.to_static / jit.scan_steps) traces once per
    bucket instead of once per distinct length — the training-side analog of
    generate()'s decode-length buckets (models/llama.py). XLA programs have
    static shapes; without bucketing, mixed-length pretraining data retraces
    per exact length (reference runs dynamic shapes natively in dygraph:
    python/paddle/jit/sot — SURVEY §7 hard part #5).

    Right-padding is loss-exact for causal LMs: padded positions sit after
    every valid token, so causal attention never lets a valid position see
    them, and labels at pads are `label_pad` (cross_entropy ignore_index).

    collate(batch_of_1d_sequences) -> (ids [B, S_bucket], labels [B, S_bucket])
    with labels = next-token targets when make_labels=True, else ids only.
    """

    def __init__(self, pad_value=0, label_pad=-100, floor=32, max_len=None,
                 make_labels=True):
        self.pad_value = int(pad_value)
        self.label_pad = int(label_pad)
        self.floor = int(floor)
        self.max_len = max_len
        self.make_labels = make_labels

    def bucket_length(self, n):
        b = max(self.floor, 1 << max(0, (int(n) - 1).bit_length()))
        return min(b, self.max_len) if self.max_len else b

    def __call__(self, batch):
        seqs = [np.asarray(s._data if isinstance(s, Tensor) else s).reshape(-1)
                for s in batch]
        if self.max_len:
            seqs = [s[:self.max_len] for s in seqs]
        need = 2 if self.make_labels else 1
        short = [i for i, s in enumerate(seqs) if len(s) < need]
        if short:
            raise ValueError(
                f"BucketCollate: samples {short} are shorter than {need} "
                "tokens" + (" (make_labels needs an input AND a target; a "
                            "1-token sample would contribute only ignored "
                            "labels and an all-short batch would NaN the "
                            "loss)" if self.make_labels else ""))
        longest = max(len(s) for s in seqs)
        S = self.bucket_length(longest if not self.make_labels
                               else longest - 1)
        if self.make_labels:
            # sample [n] -> inputs [:-1], next-token labels [1:]; pads get
            # label_pad so the loss ignores them
            ids = np.full((len(seqs), S), self.pad_value, np.int32)
            labels = np.full((len(seqs), S), self.label_pad, np.int32)
            for i, s in enumerate(seqs):
                n = len(s) - 1
                ids[i, :n] = s[:-1]
                labels[i, :n] = s[1:]
            return Tensor(ids), Tensor(labels)
        ids = np.full((len(seqs), S), self.pad_value, np.int32)
        for i, s in enumerate(seqs):
            ids[i, :len(s)] = s
        return Tensor(ids)


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, float):
        return Tensor(np.asarray(batch, np.float32))
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return [default_collate_fn(list(items)) for items in transposed]
    if isinstance(sample, dict):
        return {k: default_collate_fn([d[k] for d in batch]) for k in sample}
    if isinstance(sample, (str, bytes)):
        return list(batch)
    raise TypeError(f"cannot collate type {type(sample)}")


def _worker_loop(dataset, index_queue, data_queue, collate_fn, worker_id, seed):
    """reference: io/dataloader/dataloader_iter.py:460 _worker_loop."""
    np.random.seed(seed + worker_id)
    while True:
        task = index_queue.get()
        if task is None:
            break
        batch_id, indices = task
        try:
            samples = [dataset[i] for i in indices]
            data = collate_fn(samples)
            data = _to_numpy_tree(data)
            data_queue.put((batch_id, data, None))
        except Exception as e:  # propagate worker errors to the main process
            data_queue.put((batch_id, None, e))


def _to_numpy_tree(data):
    if isinstance(data, Tensor):
        return np.asarray(data._data)
    if isinstance(data, (list, tuple)):
        return type(data)(_to_numpy_tree(d) for d in data)
    if isinstance(data, dict):
        return {k: _to_numpy_tree(v) for k, v in data.items()}
    return data


def _to_tensor_tree(data):
    if isinstance(data, np.ndarray):
        return Tensor(data)
    if isinstance(data, (list, tuple)):
        return type(data)(_to_tensor_tree(d) for d in data)
    if isinstance(data, dict):
        return {k: _to_tensor_tree(v) for k, v in data.items()}
    return data


class DataLoader:
    """reference: python/paddle/io/reader.py:262."""

    def __init__(self, dataset, feed_list=None, places=None, return_list=True,
                 batch_sampler=None, batch_size=1, shuffle=False, drop_last=False,
                 collate_fn=None, num_workers=0, use_buffer_reader=True,
                 prefetch_factor=2, use_shared_memory=True, timeout=60,
                 worker_init_fn=None, persistent_workers=False, engine="auto"):
        self.dataset = dataset
        self.engine = engine
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size or 1,
                                              drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def __iter__(self):
        if self._iterable:
            yield from self._iter_iterable()
        # engine="native" is an explicit requirement regardless of
        # num_workers; "auto" upgrades the worker path when eligible
        elif (self.engine == "native" or self.num_workers > 0) and \
                self._native_eligible():
            yield from self._iter_native()
        elif self.num_workers == 0:
            yield from self._iter_sync()
        else:
            yield from self._iter_workers()

    def _native_eligible(self):
        """Use the C++ gather engine (core/native/dataloader.cc) when the
        dataset is a TensorDataset of fixed-shape arrays with the default
        collate — the common pretraining layout. engine: "auto" (default),
        "native" (require), "python" (mp workers)."""
        if self.engine == "python":
            return False
        ok = (isinstance(self.dataset, TensorDataset)
              and self.collate_fn is default_collate_fn)
        if ok:
            from .native_loader import available
            ok = available()
        if self.engine == "native" and not ok:
            raise RuntimeError(
                "engine='native' requires a TensorDataset with the default "
                "collate and a working C++ toolchain")
        return ok

    def _iter_native(self):
        from .native_loader import NativeArrayLoader
        if getattr(self, "_native_arrays", None) is None:
            # one-time host materialization (device->host for device-resident
            # tensors + contiguity), reused across epochs
            self._native_arrays = [
                np.ascontiguousarray(np.asarray(t._data)) if isinstance(t, Tensor)
                else np.ascontiguousarray(t) for t in self.dataset.tensors]
        loader = NativeArrayLoader(self._native_arrays,
                                   list(self.batch_sampler),
                                   num_threads=max(1, self.num_workers),
                                   depth=self.prefetch_factor *
                                   max(1, self.num_workers))
        for views in loader:
            yield [Tensor(v) for v in views]

    def _iter_iterable(self):
        batch = []
        for sample in self.dataset:
            batch.append(sample)
            if self.batch_size is not None and len(batch) == self.batch_size:
                yield self.collate_fn(batch)
                batch = []
        if batch and not self.drop_last:
            yield self.collate_fn(batch)

    def _iter_sync(self):
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _iter_workers(self):
        ctx = mp.get_context("fork")
        index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        data_queue = ctx.Queue()
        seed = int(np.random.randint(0, 2 ** 31 - 1))
        workers = []
        for wid in range(self.num_workers):
            w = ctx.Process(target=_worker_loop,
                            args=(self.dataset, index_queues[wid], data_queue,
                                  self.collate_fn, wid, seed), daemon=True)
            w.start()
            workers.append(w)
        try:
            batches = list(self.batch_sampler)
            inflight = {}
            next_submit = 0
            next_yield = 0
            max_inflight = self.num_workers * self.prefetch_factor
            reorder = {}
            while next_yield < len(batches):
                while next_submit < len(batches) and len(inflight) < max_inflight:
                    wid = next_submit % self.num_workers
                    index_queues[wid].put((next_submit, batches[next_submit]))
                    inflight[next_submit] = wid
                    next_submit += 1
                if next_yield in reorder:
                    yield _to_tensor_tree(reorder.pop(next_yield))
                    next_yield += 1
                    continue
                bid, data, err = data_queue.get(timeout=self.timeout)
                if err is not None:
                    raise RuntimeError(f"DataLoader worker failed on batch {bid}") from err
                inflight.pop(bid, None)
                if bid == next_yield:
                    yield _to_tensor_tree(data)
                    next_yield += 1
                else:
                    reorder[bid] = data
        finally:
            for q in index_queues:
                q.put(None)
            for w in workers:
                w.join(timeout=1)
                if w.is_alive():
                    w.terminate()

    def __call__(self):
        return iter(self)


def get_worker_info():
    return None


class SubsetRandomSampler(Sampler):
    """reference io/dataloader/sampler.py SubsetRandomSampler."""

    def __init__(self, indices, generator=None):
        self.indices = list(indices)
        self.generator = generator

    def __iter__(self):
        perm = np.random.RandomState(
            _epoch_seed(self.generator)).permutation(len(self.indices))
        return iter([self.indices[i] for i in perm])

    def __len__(self):
        return len(self.indices)
