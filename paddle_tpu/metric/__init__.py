"""paddle.metric (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import unwrap


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__.lower()

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = np.asarray(unwrap(pred))
        l = np.asarray(unwrap(label))
        if l.ndim == p.ndim:
            l = l.squeeze(-1)
        topk_idx = np.argsort(-p, axis=-1)[..., :self.maxk]
        correct = topk_idx == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(unwrap(correct))
        num = c.shape[0] if c.ndim else 1
        for i, k in enumerate(self.topk):
            self.total[i] += float(c[..., :k].sum())
            self.count[i] += num
        return self.total[0] / max(self.count[0], 1)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = (np.asarray(unwrap(preds)) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(unwrap(labels)).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fp, 1)

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = (np.asarray(unwrap(preds)) > 0.5).astype(np.int32).reshape(-1)
        l = np.asarray(unwrap(labels)).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def accumulate(self):
        return self.tp / max(self.tp + self.fn, 1)

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via threshold buckets (reference: metric/metrics.py Auc)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        p = np.asarray(unwrap(preds))
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = np.asarray(unwrap(labels)).reshape(-1)
        buckets = np.clip((p * self.num_thresholds).astype(np.int64), 0,
                          self.num_thresholds)
        for b, y in zip(buckets, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over descending thresholds
        area = 0.0
        pos = neg = 0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = pos + self._stat_pos[i]
            new_neg = neg + self._stat_neg[i]
            area += (new_neg - neg) * (pos + new_pos) / 2.0
            pos, neg = new_pos, new_neg
        return float(area / (tot_pos * tot_neg))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp
    import jax
    p = unwrap(input)
    l = unwrap(label)
    if l.ndim == p.ndim:
        l = l.squeeze(-1)
    _, idx = jax.lax.top_k(p, k)
    correct_mask = (idx == l[..., None]).any(-1)
    return Tensor(jnp.mean(correct_mask.astype(jnp.float32)))
