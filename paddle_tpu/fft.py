"""paddle.fft analog — discrete Fourier transforms (reference:
python/paddle/fft.py, ~1.8k LoC over phi fft kernels; here each transform is
the jnp.fft primitive routed through dispatch so autograd/AMP/capture apply)."""
from __future__ import annotations

import jax.numpy as jnp

from .core.dispatch import apply_op
from .core.tensor import Tensor

__all__ = [
    "fft", "ifft", "fft2", "ifft2", "fftn", "ifftn",
    "rfft", "irfft", "rfft2", "irfft2", "rfftn", "irfftn",
    "hfft", "ihfft", "hfft2", "ihfft2", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]


def _norm(norm):
    if norm in (None, "backward", "forward", "ortho"):
        return norm
    raise ValueError(f"invalid norm {norm!r}")


def _1d(name, jfn):
    def op(x, n=None, axis=-1, norm="backward", name_=None):
        return apply_op(name, lambda a: jfn(a, n=n, axis=axis,
                                            norm=_norm(norm)), x)
    op.__name__ = name
    return op


def _2d(name, jfn):
    def op(x, s=None, axes=(-2, -1), norm="backward", name_=None):
        return apply_op(name, lambda a: jfn(a, s=s, axes=axes,
                                            norm=_norm(norm)), x)
    op.__name__ = name
    return op


def _nd(name, jfn):
    def op(x, s=None, axes=None, norm="backward", name_=None):
        return apply_op(name, lambda a: jfn(a, s=s, axes=axes,
                                            norm=_norm(norm)), x)
    op.__name__ = name
    return op


fft = _1d("fft", jnp.fft.fft)
ifft = _1d("ifft", jnp.fft.ifft)
rfft = _1d("rfft", jnp.fft.rfft)
irfft = _1d("irfft", jnp.fft.irfft)
hfft = _1d("hfft", jnp.fft.hfft)
ihfft = _1d("ihfft", jnp.fft.ihfft)

fft2 = _2d("fft2", jnp.fft.fft2)
ifft2 = _2d("ifft2", jnp.fft.ifft2)
rfft2 = _2d("rfft2", jnp.fft.rfft2)
irfft2 = _2d("irfft2", jnp.fft.irfft2)

fftn = _nd("fftn", jnp.fft.fftn)
ifftn = _nd("ifftn", jnp.fft.ifftn)
rfftn = _nd("rfftn", jnp.fft.rfftn)
irfftn = _nd("irfftn", jnp.fft.irfftn)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    """Hermitian 2-D fft: irfft along the last axis after fft on the first."""
    def f(a):
        return jnp.fft.fft2(jnp.conj(a), s=s, axes=axes, norm=_norm(norm)).real
    # compose from hfft over the last axis and fft over the first
    def g(a):
        n0 = None if s is None else s[0]
        n1 = None if s is None else s[1]
        out = jnp.fft.hfft(a, n=n1, axis=axes[1], norm=_norm(norm))
        return jnp.fft.fft(out, n=n0, axis=axes[0], norm=_norm(norm)).real
    return apply_op("hfft2", g, x)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    def g(a):
        n0 = None if s is None else s[0]
        n1 = None if s is None else s[1]
        out = jnp.fft.ihfft(a, n=n1, axis=axes[1], norm=_norm(norm))
        return jnp.fft.ifft(out, n=n0, axis=axes[0], norm=_norm(norm))
    return apply_op("ihfft2", g, x)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    def g(a):
        ax = axes if axes is not None else list(range(a.ndim))
        nlast = None if s is None else s[-1]
        out = jnp.fft.hfft(a, n=nlast, axis=ax[-1], norm=_norm(norm))
        if len(ax) > 1:
            sn = None if s is None else s[:-1]
            out = jnp.fft.fftn(out, s=sn, axes=ax[:-1], norm=_norm(norm)).real
        return out
    return apply_op("hfftn", g, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    def g(a):
        ax = axes if axes is not None else list(range(a.ndim))
        nlast = None if s is None else s[-1]
        out = jnp.fft.ihfft(a, n=nlast, axis=ax[-1], norm=_norm(norm))
        if len(ax) > 1:
            sn = None if s is None else s[:-1]
            out = jnp.fft.ifftn(out, s=sn, axes=ax[:-1], norm=_norm(norm))
        return out
    return apply_op("ihfftn", g, x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.fftfreq(n, d=d).astype(dtype or jnp.float32))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    return Tensor(jnp.fft.rfftfreq(n, d=d).astype(dtype or jnp.float32))


def fftshift(x, axes=None, name=None):
    return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)
