from .model import Model, summary, flops  # noqa: F401
