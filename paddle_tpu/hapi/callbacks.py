"""hapi training callbacks (reference: python/paddle/hapi/callbacks.py —
Callback/CallbackList, ProgBarLogger:226, ModelCheckpoint:481,
LRScheduler:539, EarlyStopping:598, VisualDL:713)."""
from __future__ import annotations

import os
import time

import numpy as np

__all__ = ["Callback", "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "CallbackList"]


class Callback:
    """reference: callbacks.py Callback — all hooks are no-ops by default."""

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model=None, params=None):
        self.callbacks = list(callbacks or [])
        for c in self.callbacks:
            c.set_model(model)
            c.set_params(params)

    def _call(self, hook, *args):
        for c in self.callbacks:
            getattr(c, hook)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._call(name, *a)
        raise AttributeError(name)

    @property
    def stop_training(self):
        return any(getattr(c, "stop_training", False)
                   for c in self.callbacks)


class ProgBarLogger(Callback):
    """reference: callbacks.py:226 — periodic loss/metric lines."""

    def __init__(self, log_freq=1, verbose=2):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch
        self._t0 = time.perf_counter()

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            logs = logs or {}
            items = " ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                             f"{k}: {v}" for k, v in logs.items())
            print(f"Epoch {self._epoch + 1} step {step} {items}")  # graftlint: disable=no-adhoc-telemetry

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            logs = logs or {}
            items = " ".join(f"{k}: {v:.4f}" if isinstance(v, float) else
                             f"{k}: {v}" for k, v in logs.items())
            print(f"Epoch {epoch + 1} done "  # graftlint: disable=no-adhoc-telemetry
                  f"({time.perf_counter() - self._t0:.1f}s) {items}")


class ModelCheckpoint(Callback):
    """reference: callbacks.py:481 — save every N epochs."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, f"{epoch}")
            self.model.save(path)


class LRScheduler(Callback):
    """reference: callbacks.py:539 — step the lr scheduler per epoch/batch."""

    def __init__(self, by_step=False, by_epoch=True):
        self.by_step, self.by_epoch = by_step, by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    """reference: callbacks.py:598 — stop when a monitored metric stalls."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True,
                 save_dir=None):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.verbose = verbose
        self.save_best_model = save_best_model
        self.save_dir = save_dir
        self._maximize = mode == "max" or (mode == "auto" and "acc" in
                                           monitor)
        self._reset()

    def _cmp(self, cur, best):
        return cur > best + self.min_delta if self._maximize else \
            cur < best - self.min_delta

    def _reset(self):
        self.best = -np.inf if self._maximize else np.inf
        if self.baseline is not None:
            self.best = self.baseline
        self.stop_training = False
        self.wait = 0

    def on_train_begin(self, logs=None):
        # a reused instance must not inherit the previous fit()'s state
        self._reset()

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur[0] if isinstance(cur, (list, tuple, np.ndarray))
                    else cur)
        if self._cmp(cur, self.best):
            self.best = cur
            self.wait = 0
            if self.save_best_model and self.save_dir:
                self.model.save(os.path.join(self.save_dir, "best_model"))
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.stop_training = True
                if self.verbose:
                    print(f"EarlyStopping: stop at epoch {epoch + 1} "  # graftlint: disable=no-adhoc-telemetry
                          f"({self.monitor}={cur:.4f} best={self.best:.4f})")
