"""paddle.Model high-level API (reference: python/paddle/hapi/model.py)."""
from __future__ import annotations

import math
import os
import time

import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import unwrap
from ..nn.layer.layers import Layer
from ..io import DataLoader, Dataset


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs if inputs is None or isinstance(
            inputs, (list, tuple)) else [inputs]
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics else [])
        return self

    def _loss_value(self, outputs, labels):
        loss = self._loss(outputs, labels)
        return loss

    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*ins)
        loss = self._loss_value(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            m.update(unwrap(m.compute(outputs, labels)))
            metrics.append(m.accumulate())
        return ([float(loss.item())], metrics) if metrics else [float(loss.item())]

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*ins)
        loss = self._loss_value(outputs, labels)
        metrics = []
        for m in self._metrics:
            m.update(unwrap(m.compute(outputs, labels)))
            metrics.append(m.accumulate())
        return ([float(loss.item())], metrics) if metrics else [float(loss.item())]

    def predict_batch(self, inputs):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        out = self.network(*ins)
        return [np.asarray(unwrap(out))]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = train_data if isinstance(train_data, DataLoader) else DataLoader(
            train_data, batch_size=batch_size, shuffle=shuffle, drop_last=drop_last,
            num_workers=num_workers)
        from .callbacks import CallbackList
        cbks = CallbackList(callbacks, model=self,
                            params={"epochs": epochs, "verbose": verbose})
        history = []
        it = 0
        cbks.on_train_begin()
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            cbks.on_epoch_begin(epoch)
            epoch_losses = []
            t0 = time.perf_counter()
            for step, batch in enumerate(loader):
                data, label = (batch[0], batch[1]) if isinstance(batch, (list, tuple)) \
                    and len(batch) >= 2 else (batch, None)
                cbks.on_train_batch_begin(step)
                out = self.train_batch(data, label)
                loss = out[0] if isinstance(out, tuple) else out
                epoch_losses.append(loss[0])
                it += 1
                cbks.on_train_batch_end(step, {"loss": float(loss[0])})
                if verbose and step % log_freq == 0:
                    print(f"Epoch {epoch + 1}/{epochs} step {step} "  # graftlint: disable=no-adhoc-telemetry
                          f"loss {loss[0]:.4f}")
                if num_iters is not None and it >= num_iters:
                    break
            history.append(float(np.mean(epoch_losses)))
            cbks.on_epoch_end(epoch, {"loss": history[-1]})
            if verbose:
                print(f"Epoch {epoch + 1}: mean loss {history[-1]:.4f} "  # graftlint: disable=no-adhoc-telemetry
                      f"({time.perf_counter() - t0:.1f}s)")
            if eval_data is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_data, batch_size=batch_size, verbose=verbose)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, f"epoch_{epoch}"))
            if num_iters is not None and it >= num_iters:
                break
            if cbks.stop_training:
                self.stop_training = True
                break
        cbks.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = eval_data if isinstance(eval_data, DataLoader) else DataLoader(
            eval_data, batch_size=batch_size, num_workers=num_workers)
        losses = []
        for m in self._metrics:
            m.reset()
        for batch in loader:
            data, label = (batch[0], batch[1]) if isinstance(batch, (list, tuple)) \
                and len(batch) >= 2 else (batch, None)
            out = self.eval_batch(data, label)
            loss = out[0] if isinstance(out, tuple) else out
            losses.append(loss[0])
        result = {"loss": [float(np.mean(losses))]}
        for m in self._metrics:
            result[m.name() if isinstance(m.name(), str) else m.name()[0]] = m.accumulate()
        if verbose:
            print("Eval:", result)  # graftlint: disable=no-adhoc-telemetry
        return result

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1):
        loader = test_data if isinstance(test_data, DataLoader) else DataLoader(
            test_data, batch_size=batch_size, num_workers=num_workers)
        outs = []
        for batch in loader:
            data = batch[0] if isinstance(batch, (list, tuple)) else batch
            outs.append(self.predict_batch(data)[0])
        if stack_outputs:
            return [np.concatenate(outs, axis=0)]
        return [outs]

    def save(self, path, training=True):
        """training=True: params(+opt) checkpoint; training=False: inference
        export via jit.save (StableHLO) using the Model's input specs
        (reference: hapi/model.py Model.save -> _save_inference_model)."""
        from ..framework.io import save as psave
        if training:
            psave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                psave(self._optimizer.state_dict(), path + ".pdopt")
        else:
            if self._inputs is None:
                raise ValueError(
                    "Model.save(training=False) needs input specs: "
                    "Model(net, inputs=[InputSpec(...)])")
            from .. import jit
            jit.save(self.network, path, input_spec=list(self._inputs))

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as pload
        self.network.set_state_dict(pload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(pload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters()

    def summary(self, input_size=None, dtype=None):
        return summary(self.network, input_size, dtype)


def summary(net, input_size=None, dtypes=None, input=None):
    """reference: python/paddle/hapi/model_summary.py."""
    rows = []
    total_params = 0
    trainable_params = 0
    for name, p in net.named_parameters():
        n = p.size
        total_params += n
        if not p.stop_gradient:
            trainable_params += n
        rows.append((name, tuple(p.shape), n))
    width = max((len(r[0]) for r in rows), default=20) + 2
    lines = [f"{'Layer (param)':<{width}}{'Shape':<20}{'Param #':<12}",
             "-" * (width + 32)]
    for name, shape, n in rows:
        lines.append(f"{name:<{width}}{str(shape):<20}{n:<12,}")
    lines.append("-" * (width + 32))
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    out = "\n".join(lines)
    print(out)  # graftlint: disable=no-adhoc-telemetry (summary() prints by contract)
    return {"total_params": total_params, "trainable_params": trainable_params}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough analytic FLOPs for Linear/Conv layers (reference: hapi/dynamic_flops.py)."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import _ConvNd
    total = 0
    for layer in net.sublayers(include_self=True):
        if isinstance(layer, Linear):
            total += 2 * layer._in_features * layer._out_features
        elif isinstance(layer, _ConvNd):
            import numpy as _np
            k = _np.prod(layer._kernel_size)
            total += 2 * layer._in_channels * layer._out_channels * k
    if print_detail:
        print(f"FLOPs (per spatial position / token): {total:,}")  # graftlint: disable=no-adhoc-telemetry
    return total
