"""Model zoo: flagship configs from BASELINE.md (GPT-2, Llama-3, MoE,
ERNIE encoder family)."""
