"""ERNIE encoder family (BASELINE "ERNIE-style" configs; architecture parity
target: PaddleNLP ErnieModel — the reference repo hosts the framework, the
model recipe lives downstream, same arrangement as gpt2.py/llama.py).

ERNIE 1.0–3.0 is a BERT-style bidirectional encoder: word + position +
token-type (+ task-type in 3.0) embeddings, post-LN transformer encoder, a
tanh pooler over [CLS], and task heads (masked-LM with tied decoder,
sequence classification). Built purely from paddle_tpu.nn so it exercises
the user-facing stack end to end; attention runs through
nn.MultiHeadAttention (flash path on TPU), masks are additive [B,1,1,S].
"""
from __future__ import annotations

from .. import ops
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding, Dropout
from ..nn.layer.norm import LayerNorm
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer
from ..nn import functional as F
from ..nn.initializer import Normal


class ErnieConfig:
    def __init__(self, vocab_size=18000, hidden_size=768, num_hidden_layers=12,
                 num_attention_heads=12, intermediate_size=3072,
                 hidden_act="gelu", hidden_dropout_prob=0.1,
                 attention_probs_dropout_prob=0.1,
                 max_position_embeddings=513, type_vocab_size=4,
                 task_type_vocab_size=0, initializer_range=0.02,
                 layer_norm_eps=1e-12, pad_token_id=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        # ERNIE 3.0 adds a task-type embedding stream; 0 disables (1.0/2.0)
        self.task_type_vocab_size = task_type_vocab_size
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.pad_token_id = pad_token_id

    @classmethod
    def base(cls, **kw):       # ernie-3.0-base-zh geometry
        kw.setdefault("vocab_size", 40000)
        kw.setdefault("max_position_embeddings", 2048)
        kw.setdefault("task_type_vocab_size", 3)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):       # test config
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 2)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("max_position_embeddings", 64)
        return cls(**kw)


class ErnieEmbeddings(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        init = Normal(std=cfg.initializer_range)
        self.word_embeddings = Embedding(cfg.vocab_size, cfg.hidden_size,
                                         padding_idx=cfg.pad_token_id,
                                         weight_attr=init)
        self.position_embeddings = Embedding(cfg.max_position_embeddings,
                                             cfg.hidden_size, weight_attr=init)
        self.token_type_embeddings = Embedding(cfg.type_vocab_size,
                                               cfg.hidden_size, weight_attr=init)
        self.task_type_embeddings = (
            Embedding(cfg.task_type_vocab_size, cfg.hidden_size,
                      weight_attr=init)
            if cfg.task_type_vocab_size else None)
        self.layer_norm = LayerNorm(cfg.hidden_size, epsilon=cfg.layer_norm_eps)
        self.dropout = Dropout(cfg.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                task_type_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = ops.arange(s, dtype="int64").unsqueeze(0)
        if token_type_ids is None:
            token_type_ids = ops.zeros([b, s], dtype="int64")
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        if self.task_type_embeddings is not None:
            if task_type_ids is None:
                task_type_ids = ops.zeros([b, s], dtype="int64")
            x = x + self.task_type_embeddings(task_type_ids)
        return self.dropout(self.layer_norm(x))


class ErniePooler(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.dense = Linear(cfg.hidden_size, cfg.hidden_size,
                            weight_attr=Normal(std=cfg.initializer_range))

    def forward(self, hidden):
        return ops.tanh(self.dense(hidden[:, 0]))


class ErnieModel(Layer):
    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.config = cfg
        self.embeddings = ErnieEmbeddings(cfg)
        layer = TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads, cfg.intermediate_size,
            dropout=cfg.hidden_dropout_prob, activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            normalize_before=False,           # ERNIE/BERT are post-LN
            weight_attr=Normal(std=cfg.initializer_range),
            layer_norm_eps=cfg.layer_norm_eps)
        self.encoder = TransformerEncoder(layer, cfg.num_hidden_layers)
        self.pooler = ErniePooler(cfg)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, task_type_ids=None):
        """attention_mask: [B, S] with 1 = attend, 0 = pad (HF/PaddleNLP
        convention) — converted to an additive [B, 1, 1, S] bias.

        With attention_mask=None the encoder attends everywhere and runs
        mask-free (the TPU flash-attention path). This matches HF BERT
        semantics and DIVERGES from PaddleNLP, which synthesizes a pad mask
        from pad_token_id — a data-dependent host check that would break
        program capture here. Padded batches must pass attention_mask."""
        bias = None
        if attention_mask is not None:
            bias = ((1.0 - attention_mask.astype("float32")) * -1e4)
            bias = bias.unsqueeze(1).unsqueeze(1)        # [B,1,1,S]
        x = self.embeddings(input_ids, token_type_ids, position_ids,
                            task_type_ids)
        seq = self.encoder(x, src_mask=bias)
        return seq, self.pooler(seq)


class ErnieForMaskedLM(Layer):
    """MLM head with the PaddleNLP transform (dense + act + LN) and a tied
    decoder over the word-embedding matrix."""

    def __init__(self, cfg: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.config = cfg
        init = Normal(std=cfg.initializer_range)
        self.transform = Linear(cfg.hidden_size, cfg.hidden_size,
                                weight_attr=init)
        self.transform_ln = LayerNorm(cfg.hidden_size,
                                      epsilon=cfg.layer_norm_eps)
        from ..core.tensor import Parameter
        import jax.numpy as jnp
        self.decoder_bias = Parameter(jnp.zeros((cfg.vocab_size,),
                                                jnp.float32))

    def forward(self, input_ids, token_type_ids=None, labels=None,
                attention_mask=None, position_ids=None, task_type_ids=None,
                ignore_index=-100):
        seq, _ = self.ernie(input_ids, token_type_ids, position_ids,
                            attention_mask=attention_mask,
                            task_type_ids=task_type_ids)
        act = getattr(F, self.config.hidden_act)
        h = self.transform_ln(act(self.transform(seq)))
        logits = ops.matmul(h, self.ernie.embeddings.word_embeddings.weight,
                            transpose_y=True) + self.decoder_bias
        if labels is None:
            return logits
        loss = F.cross_entropy(
            logits.reshape([-1, self.config.vocab_size]),
            labels.reshape([-1]), ignore_index=ignore_index)
        return logits, loss


class ErnieForSequenceClassification(Layer):
    def __init__(self, cfg: ErnieConfig, num_classes=2, dropout=None):
        super().__init__()
        self.ernie = ErnieModel(cfg)
        self.num_classes = num_classes
        self.dropout = Dropout(dropout if dropout is not None
                               else cfg.hidden_dropout_prob)
        self.classifier = Linear(cfg.hidden_size, num_classes,
                                 weight_attr=Normal(std=cfg.initializer_range))

    def forward(self, input_ids, token_type_ids=None, labels=None,
                attention_mask=None, position_ids=None, task_type_ids=None):
        _, pooled = self.ernie(input_ids, token_type_ids, position_ids,
                               attention_mask=attention_mask,
                               task_type_ids=task_type_ids)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return logits, F.cross_entropy(logits, labels.reshape([-1]))
