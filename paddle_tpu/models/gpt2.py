"""GPT-2 (BASELINE config #1: 124M single-chip LM pretraining).

Architecture parity target: PaddleNLP GPT-2 (the reference repo hosts the
framework; the model recipe lives downstream). Built purely from paddle_tpu.nn
so it exercises the user-facing stack end to end.
"""
from __future__ import annotations

import math

import numpy as np

from .. import ops
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding, Dropout
from ..nn.layer.norm import LayerNorm
from ..nn.layer.container import LayerList
from ..nn import functional as F
from ..nn.initializer import Normal


class GPT2Config:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None, max_position_embeddings=1024,
                 hidden_dropout_prob=0.1, attention_dropout_prob=0.1,
                 layer_norm_epsilon=1e-5, initializer_range=0.02,
                 use_recompute=False, loss_chunk_size=0,
                 loss_recompute=True, loss_logits_dtype="float32"):
        self.use_recompute = use_recompute
        self.loss_chunk_size = loss_chunk_size
        # recompute chunk logits in backward (jax.checkpoint) instead of
        # keeping them: O(chunk*V) live memory but one extra [chunk,V] matmul
        # per chunk. Turn off when HBM allows (saves ~9% of step FLOPs).
        self.loss_recompute = loss_recompute
        # "bfloat16": keep the [chunk, V] logits in bf16 with f32 LSE
        # accumulation (the flash-attention numerics recipe) — halves the
        # bytes streamed by the CE softmax pass AND the resident residual
        # when loss_recompute is off. The r4 profile put the f32 softmax
        # pass at 7.6 ms/step at b16 s1024 (subtract_exponential fusion over
        # f32[16384,50304]).
        self.loss_logits_dtype = loss_logits_dtype
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_dropout_prob = attention_dropout_prob
        self.layer_norm_epsilon = layer_norm_epsilon
        self.initializer_range = initializer_range

    @classmethod
    def gpt2_small(cls, **kw):  # 124M
        return cls(hidden_size=768, num_layers=12, num_heads=12, **kw)

    @classmethod
    def tiny(cls, **kw):  # test config
        kw.setdefault("vocab_size", 256)
        kw.setdefault("max_position_embeddings", 64)
        return cls(hidden_size=64, num_layers=2, num_heads=2, **kw)


class GPT2Attention(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.num_heads = config.num_heads
        self.head_dim = config.hidden_size // config.num_heads
        init = Normal(std=config.initializer_range)
        self.qkv = Linear(config.hidden_size, 3 * config.hidden_size,
                          weight_attr=init)
        self.proj = Linear(config.hidden_size, config.hidden_size,
                           weight_attr=Normal(std=config.initializer_range /
                                              math.sqrt(2 * config.num_layers)))
        self.attn_drop = config.attention_dropout_prob
        self.resid_drop = Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv(x).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv.unbind(axis=2)
        out = F.scaled_dot_product_attention(
            q, k, v, is_causal=True, dropout_p=self.attn_drop,
            training=self.training)
        out = out.reshape([b, s, h])
        return self.resid_drop(self.proj(out))


class GPT2MLP(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        init = Normal(std=config.initializer_range)
        self.fc = Linear(config.hidden_size, config.intermediate_size, weight_attr=init)
        self.proj = Linear(config.intermediate_size, config.hidden_size,
                           weight_attr=Normal(std=config.initializer_range /
                                              math.sqrt(2 * config.num_layers)))
        self.drop = Dropout(config.hidden_dropout_prob)

    def forward(self, x):
        return self.drop(self.proj(F.gelu(self.fc(x), approximate=True)))


class GPT2Block(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.ln1 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.attn = GPT2Attention(config)
        self.ln2 = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)
        self.mlp = GPT2MLP(config)

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x


class GPT2Model(Layer):
    def __init__(self, config: GPT2Config):
        super().__init__()
        self.config = config
        init = Normal(std=config.initializer_range)
        self.wte = Embedding(config.vocab_size, config.hidden_size, weight_attr=init)
        self.wpe = Embedding(config.max_position_embeddings, config.hidden_size,
                             weight_attr=Normal(std=config.initializer_range))
        self.drop = Dropout(config.hidden_dropout_prob)
        self.blocks = LayerList([GPT2Block(config) for _ in range(config.num_layers)])
        self.ln_f = LayerNorm(config.hidden_size, epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = ops.arange(s, dtype="int64").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        remat = self.config.use_recompute and self.training
        if remat:
            from ..distributed.fleet.recompute import recompute
        for block in self.blocks:
            x = recompute(block, x) if remat else block(x)
        return self.ln_f(x)


def _chunked_lm_loss(hidden, wte, labels, chunk, ignore_index=-100,
                     recompute=True, logits_dtype="float32"):
    """Tied-head LM loss WITHOUT materializing [B*S, V] logits: lax.scan over
    token chunks, each chunk jax.checkpoint'ed so the backward recomputes its
    [chunk, V] logits instead of keeping them — peak memory drops from
    O(B*S*V) to O(chunk*V), buying back batch on HBM-tight chips (same trick
    as the reference's c_softmax_with_cross_entropy streaming).

    logits_dtype="bfloat16" keeps the [chunk, V] logits in bf16 and runs the
    log-sum-exp with f32 accumulation (subtract the bf16 row max, convert,
    exp/sum in f32 — the flash-attention recipe), halving the HBM bytes of
    the softmax pass and the kept residuals."""
    from ..core.dispatch import apply_op
    import jax
    import jax.numpy as jnp

    def f(h, w, y):
        B, S, H = h.shape
        flat_h = h.reshape(B * S, H)
        flat_y = y.reshape(B * S)
        n = flat_h.shape[0]
        c = min(chunk, n)
        pad = (-n) % c
        if pad:
            flat_h = jnp.pad(flat_h, ((0, pad), (0, 0)))
            flat_y = jnp.pad(flat_y, (0, pad))
        hs = flat_h.reshape(-1, c, H)
        ys = flat_y.reshape(-1, c)
        bf16_logits = jnp.dtype(logits_dtype) == jnp.dtype(jnp.bfloat16)

        def one(hc, yc):
            # ignore_index rows (and padding, marked the same way) are
            # masked out of both the sum and the valid-token count, matching
            # F.cross_entropy's default ignore_index=-100 semantics
            valid = yc != ignore_index
            safe_y = jnp.where(valid, yc, 0).astype(jnp.int32)
            if bf16_logits:
                logits = (hc @ w.T).astype(jnp.bfloat16)  # [c, V] in bf16
                # (explicit cast: on a bf16 model it's a no-op XLA elides;
                # on an f32 model it's what makes the flag actually halve
                # the streamed/kept bytes)
                m = jnp.max(logits, axis=-1, keepdims=True)
                z = (logits - m).astype(jnp.float32)    # f32 from here on
                lse = m[:, 0].astype(jnp.float32) + jnp.log(
                    jnp.sum(jnp.exp(z), axis=-1))
                picked = jnp.take_along_axis(
                    logits, safe_y[:, None], axis=1)[:, 0].astype(jnp.float32)
            else:
                logits = (hc @ w.T).astype(jnp.float32)
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                picked = jnp.take_along_axis(logits, safe_y[:, None],
                                             axis=1)[:, 0]
            per_tok = jnp.where(valid, lse - picked, 0.0)
            return jnp.sum(per_tok), jnp.sum(valid)

        if recompute:
            one = jax.checkpoint(one)

        if pad:
            flat_y = flat_y.at[n:].set(ignore_index)
            hs = flat_h.reshape(-1, c, H)
            ys = flat_y.reshape(-1, c)

        def body(carry, xs):
            tot, cnt = carry
            hc, yc = xs
            t, k = one(hc, yc)
            return (tot + t, cnt + k), None

        (total, count), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.int32)),
            (hs, ys))
        return total / jnp.maximum(count, 1)

    return apply_op("chunked_lm_loss", f, hidden, wte, labels)


class GPT2ForCausalLM(Layer):
    """LM head ties wte weights (standard GPT-2)."""

    def __init__(self, config: GPT2Config):
        super().__init__()
        self.gpt2 = GPT2Model(config)
        self.config = config

    def forward(self, input_ids, labels=None, position_ids=None):
        hidden = self.gpt2(input_ids, position_ids)
        if labels is not None and self.config.loss_chunk_size:
            loss = _chunked_lm_loss(
                hidden, self.gpt2.wte.weight, labels,
                self.config.loss_chunk_size,
                recompute=self.config.loss_recompute,
                logits_dtype=getattr(self.config, "loss_logits_dtype",
                                     "float32"))
            return None, loss
        logits = ops.matmul(hidden, self.gpt2.wte.weight, transpose_y=True)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]))
            return logits, loss
        return logits

    def generate(self, input_ids, max_new_tokens=20, temperature=1.0, top_k=None):
        from .. import no_grad
        out = input_ids
        with no_grad():
            self.eval()
            for _ in range(max_new_tokens):
                ctx = out if out.shape[1] <= self.config.max_position_embeddings \
                    else out[:, -self.config.max_position_embeddings:]
                logits = self.forward(ctx)
                nxt = logits[:, -1, :] / temperature
                if top_k is not None:
                    v, _ = ops.topk(nxt, top_k)
                    nxt = ops.where(nxt < v[:, -1:], ops.full_like(nxt, -1e30), nxt)
                probs = F.softmax(nxt, axis=-1)
                token = ops.multinomial(probs, 1)
                out = ops.concat([out, token], axis=1)
        return out


def gpt2_small():
    return GPT2ForCausalLM(GPT2Config.gpt2_small())


def gpt2_tiny():
    return GPT2ForCausalLM(GPT2Config.tiny())
