"""Llama-3 family (BASELINE config #2: 8B pretrain, FSDP→GSPMD; #5 MoE variant).

Architecture: RMSNorm + GQA attention with RoPE + SwiGLU MLP, tied to the
paddle_tpu.nn stack. `shard_llama` applies the hybrid placement policy
(dp/fsdp/mp/sep axes) — the fleet 4D mapping from SURVEY §2.4 as GSPMD.
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from .. import ops
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding
from ..nn.layer.norm import RMSNorm
from ..nn.layer.container import LayerList
from ..nn import functional as F
from ..nn.functional.rope import fused_rotary_position_embedding
from ..nn.initializer import Normal


class LlamaConfig:
    def __init__(self, vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                 num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
                 max_position_embeddings=8192, rms_norm_eps=1e-5, rope_theta=500000.0,
                 tie_word_embeddings=False, initializer_range=0.02,
                 num_experts=0, num_experts_per_tok=2, moe_intermediate_size=None,
                 sep_backend="ring"):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.tie_word_embeddings = tie_word_embeddings
        self.initializer_range = initializer_range
        self.num_experts = num_experts
        self.sep_backend = sep_backend
        self.num_experts_per_tok = num_experts_per_tok
        self.moe_intermediate_size = moe_intermediate_size

    @classmethod
    def llama3_8b(cls, **kw):
        return cls(**kw)

    @classmethod
    def qwen2_moe_a14b(cls, **kw):
        """Qwen2-57B-A14B MoE geometry (public config: 64 experts, top-8,
        GQA 28q/4kv, 3584 hidden) — BASELINE config #5 family."""
        kw.setdefault("vocab_size", 151936)
        kw.setdefault("hidden_size", 3584)
        kw.setdefault("intermediate_size", 18944)
        kw.setdefault("num_hidden_layers", 28)
        kw.setdefault("num_attention_heads", 28)
        kw.setdefault("num_key_value_heads", 4)
        kw.setdefault("max_position_embeddings", 32768)
        kw.setdefault("rope_theta", 1000000.0)
        kw.setdefault("num_experts", 64)
        kw.setdefault("num_experts_per_tok", 8)
        kw.setdefault("moe_intermediate_size", 2560)
        return cls(**kw)

    @classmethod
    def deepseek_moe_16b(cls, **kw):
        """DeepSeekMoE-16B geometry (public config: 64 routed experts, top-6,
        2048 hidden, 1408 moe-ffn) — BASELINE config #5 family."""
        kw.setdefault("vocab_size", 102400)
        kw.setdefault("hidden_size", 2048)
        kw.setdefault("intermediate_size", 10944)
        kw.setdefault("num_hidden_layers", 28)
        kw.setdefault("num_attention_heads", 16)
        kw.setdefault("num_key_value_heads", 16)
        kw.setdefault("max_position_embeddings", 4096)
        kw.setdefault("rope_theta", 10000.0)
        kw.setdefault("num_experts", 64)
        kw.setdefault("num_experts_per_tok", 6)
        kw.setdefault("moe_intermediate_size", 1408)
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("rope_theta", 10000.0)
        return cls(**kw)

    @classmethod
    def tiny_moe(cls, **kw):
        kw.setdefault("num_experts", 4)
        return cls.tiny(**kw)


class KVCache:
    """Per-layer dense KV cache for autoregressive decode (the serving path's
    block/paged variant is ops/pallas/paged_attention.py; reference:
    block_multi_head_attention's cache_kv tensors)."""

    def __init__(self, batch, max_len, num_kv_heads, head_dim, dtype="float32"):
        import jax.numpy as jnp
        self.k = Tensor(jnp.zeros((batch, max_len, num_kv_heads, head_dim),
                                  dtype))
        self.v = Tensor(jnp.zeros((batch, max_len, num_kv_heads, head_dim),
                                  dtype))
        # traced scalar, and caches mutate IN PLACE (property writes), so a
        # to_static-captured decode step has fixed shapes and replays as ONE
        # compiled program per token — no per-op tunnel round trips
        self.offset = Tensor(jnp.zeros((), jnp.int32))
        self.max_len = max_len

    def update(self, k_new, v_new):
        """Write s new steps at the current offset; returns the FULL cache
        (+ new valid length) — consumers mask instead of slicing, keeping
        shapes static under jit."""
        from ..core.dispatch import apply_op
        s = k_new.shape[1]

        def f(kc, vc, kn, vn, off):
            import jax
            kc2 = jax.lax.dynamic_update_slice(
                kc, kn.astype(kc.dtype), (0, off, 0, 0))
            vc2 = jax.lax.dynamic_update_slice(
                vc, vn.astype(vc.dtype), (0, off, 0, 0))
            return kc2, vc2, off + s

        k2, v2, off2 = apply_op("kv_cache_update", f, self.k, self.v,
                                k_new, v_new, self.offset)
        self.k._data = k2._buf
        self.v._data = v2._buf
        self.offset._data = off2._buf
        return self.k, self.v


def _cached_sdpa(q, k, v, q_offset):
    """Attention of the last `s` positions (starting at traced scalar
    q_offset) against the FULL fixed-length cache; causal masking also hides
    the not-yet-written tail, so shapes never depend on the offset."""
    from ..core.dispatch import apply_op

    def f(qa, ka, va, off):
        import jax
        b, s, h, d = qa.shape
        t = ka.shape[1]
        rep = h // ka.shape[2]
        if rep > 1:
            ka2 = jnp.repeat(ka, rep, axis=2)
            va2 = jnp.repeat(va, rep, axis=2)
        else:
            ka2, va2 = ka, va
        sc = jnp.einsum("bshd,bthd->bhst", qa.astype(jnp.float32),
                        ka2.astype(jnp.float32)) / np.sqrt(d)
        rows = off + jnp.arange(s)[:, None]
        cols = jnp.arange(t)[None, :]
        sc = jnp.where((cols <= rows)[None, None], sc, -1e30)
        p = jax.nn.softmax(sc, axis=-1)
        out = jnp.einsum("bhst,bthd->bshd", p, va2.astype(jnp.float32))
        return out.astype(qa.dtype)

    return apply_op("cached_sdpa", f, q, k, v, q_offset)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = h // self.num_heads
        self.rope_theta = config.rope_theta
        self.sep_backend = getattr(config, "sep_backend", "ring")
        init = Normal(std=config.initializer_range)
        self.q_proj = Linear(h, self.num_heads * self.head_dim, weight_attr=init,
                             bias_attr=False)
        self.k_proj = Linear(h, self.num_kv_heads * self.head_dim, weight_attr=init,
                             bias_attr=False)
        self.v_proj = Linear(h, self.num_kv_heads * self.head_dim, weight_attr=init,
                             bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, h, weight_attr=init,
                             bias_attr=False)

    def forward(self, x, position_ids=None, kv_cache: KVCache = None):
        b, s, h = x.shape
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        if kv_cache is not None and position_ids is None:
            # static arange + traced offset: shape stays [1, s] under jit
            pos = ops.arange(0, s, dtype="int64").reshape([1, s]) + \
                kv_cache.offset.astype("int64")
            position_ids = ops.tile(pos, [b, 1])
        q, k, _ = fused_rotary_position_embedding(
            q, k, None, position_ids=position_ids,
            rotary_emb_base=self.rope_theta,
            max_position=kv_cache.max_len if kv_cache is not None else None)
        if kv_cache is not None:
            q_offset = kv_cache.offset + 0   # snapshot before in-place update
            kk, vv = kv_cache.update(k, v)
            out = _cached_sdpa(q, kk, vv, q_offset)
            return self.o_proj(out.reshape([b, s, self.num_heads * self.head_dim]))
        from ..distributed.fleet.topology import get_hybrid_communicate_group
        hcg_sep = get_hybrid_communicate_group().get_sep_parallel_world_size()
        if hcg_sep > 1:
            # context parallelism: sequence sharded on 'sep'; ring attention
            # by default, Ulysses all-to-all when configured and head counts
            # divide (S >> H regime where ring's per-hop latency dominates)
            rep = self.num_heads // self.num_kv_heads
            if rep > 1:
                k = ops.repeat_interleave(k, rep, axis=2)
                v = ops.repeat_interleave(v, rep, axis=2)
            if getattr(self, "sep_backend", "ring") == "ulysses" and \
                    self.num_heads % hcg_sep == 0:
                from ..parallel.ulysses import ulysses_attention
                out = ulysses_attention(q, k, v, causal=True,
                                        axis_name="sep")
            else:
                from ..parallel.ring_attention import ring_flash_attention
                out = ring_flash_attention(q, k, v, causal=True,
                                           axis_name="sep")
        else:
            out, _ = F.flash_attention(q, k, v, causal=True)
        return self.o_proj(out.reshape([b, s, self.num_heads * self.head_dim]))


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        init = Normal(std=config.initializer_range)
        self.gate_proj = Linear(h, m, weight_attr=init, bias_attr=False)
        self.up_proj = Linear(h, m, weight_attr=init, bias_attr=False)
        self.down_proj = Linear(m, h, weight_attr=init, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        if config.num_experts > 0:
            from ..parallel.moe import MoELayer
            self.mlp = MoELayer(config.hidden_size, num_experts=config.num_experts,
                                d_hidden=config.moe_intermediate_size
                                or config.intermediate_size,
                                top_k=config.num_experts_per_tok)
        else:
            self.mlp = LlamaMLP(config)

    def forward(self, x, position_ids=None, kv_cache=None):
        x = x + self.self_attn(self.input_layernorm(x), position_ids,
                               kv_cache=kv_cache)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size,
                                      weight_attr=Normal(std=config.initializer_range))
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, position_ids=None, kv_caches=None):
        x = self.embed_tokens(input_ids)
        for i, layer in enumerate(self.layers):
            x = layer(x, position_ids,
                      kv_cache=kv_caches[i] if kv_caches else None)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=Normal(std=config.initializer_range),
                                  bias_attr=False)

    def new_kv_caches(self, batch, max_len, dtype="float32"):
        cfg = self.config
        return [KVCache(batch, max_len, cfg.num_key_value_heads,
                        cfg.hidden_size // cfg.num_attention_heads, dtype)
                for _ in range(cfg.num_hidden_layers)]

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 top_p=1.0, top_k=0, temperature=1.0, eos_token_id=None,
                 use_cache=True, seed=None, tokens_per_dispatch=None):
        """Autoregressive decoding with a per-layer KV cache (reference:
        PaddleNLP generation + phi top_p_sampling_kernel.h for the sampler).
        Greedy when do_sample=False; nucleus/top-k sampling otherwise.
        Returns [B, prompt + new] token ids.

        tokens_per_dispatch: decode steps compiled into ONE program per
        host dispatch (default 1 — async dispatch already pipelines the
        per-token calls; raise it only when per-call latency, not
        throughput, dominates). eos checking needs each token on host, so
        it forces 1."""
        from .. import ops
        from ..autograd import no_grad
        from ..jit import to_static

        with no_grad():
            b, prompt = input_ids.shape
            ids = input_ids
            finished = None
            cur = input_ids
            cached_step, caches = None, None
            gen_entry = None
            # measured on the tunneled v5e: decode dispatches already
            # pipeline (K=4 gave +2%, K=8 regressed), so default stays 1;
            # the knob remains for latency-bound deployments
            K = 1 if tokens_per_dispatch is None else tokens_per_dispatch
            K = max(1, min(int(K), max_new_tokens))
            if eos_token_id is not None:
                K = 1                      # host must see every token
            if use_cache:
                # cache length buckets to a power of two (floor 128) so
                # repeated generate() calls of similar lengths share ONE
                # compiled decode step per (batch, bucket, sampling config)
                # without paying full-context attention for short outputs;
                # entries persist on the model and reset by rewinding the
                # offset — stale tail entries are causally masked, never read
                # K>1 overshoots up to K-1 tokens past max_new before the
                # trim; the bucket must cover them or the final dispatch
                # indexes the RoPE table / cache past max_len
                need = prompt + -(-max_new_tokens // K) * K
                max_len = 1 << max(7, (need - 1).bit_length())
                gen_key = (b, max_len, do_sample, top_p, top_k, temperature,
                           seed, K)
                states = getattr(self, "_gen_states", None)
                if states is None:
                    states = self._gen_states = {}
                existing = states.get(gen_key)
                # a busy entry means a reentrant/concurrent generate: build a
                # PRIVATE state (and don't store it) so the in-flight decode
                # keeps its caches intact
                gen_entry = existing if existing is not None and \
                    not existing["busy"] else None
                if gen_entry is None:
                    caches = self.new_kv_caches(b, max_len)

                    out_dtype = str(input_ids.dtype).split(".")[-1]

                    def _one_tok(cur_tok):
                        hidden = self.llama(cur_tok, kv_caches=caches)
                        if self.lm_head is not None:
                            logits = self.lm_head(hidden[:, -1])
                        else:
                            logits = ops.matmul(
                                hidden[:, -1],
                                self.llama.embed_tokens.weight,
                                transpose_y=True)
                        nxt = self._sample(logits, do_sample, top_p, top_k,
                                           temperature, seed)
                        # cast in-graph: keeps the decode loop free of
                        # per-step eager ops (each is a device round trip)
                        return nxt.astype(out_dtype)

                    def _model_step(cur_tok):
                        # K tokens per compiled program: the kv caches are
                        # mutable captured state, so the K sequential cache
                        # updates land in ONE dispatch
                        outs = [_one_tok(cur_tok)]
                        for _ in range(K - 1):
                            outs.append(_one_tok(outs[-1]))
                        return ops.concat(outs, axis=1) if K > 1 else outs[0]

                    # one compiled program per shape signature: a prefill
                    # trace ([B, prompt]) and a decode trace ([B, 1]); every
                    # subsequent token replays the compiled decode step
                    # (cache + offset lifted as mutable program state)
                    cached_step = to_static(_model_step)
                    gen_entry = {"caches": caches, "step": cached_step,
                                 "busy": False}
                    if existing is None:
                        states[gen_key] = gen_entry
                        while len(states) > 4:  # bound retained cache memory
                            states.pop(next(iter(states)))
                else:
                    caches, cached_step = gen_entry["caches"], \
                        gen_entry["step"]
                    import jax.numpy as jnp
                    for c in caches:
                        c.offset._data = jnp.zeros((), jnp.int32)
                gen_entry["busy"] = True

            # tokens accumulate in a python list and concatenate ONCE at the
            # end: a per-step concat has a growing shape, so eager dispatch
            # would compile a fresh kernel every token (measured 15ms/token
            # vs 0.4ms for the whole compiled decode step)
            toks = [ids]
            n_dispatch = -(-max_new_tokens // K) if use_cache else \
                max_new_tokens
            try:
                for step in range(n_dispatch):
                    if use_cache:
                        blk = cached_step(cur)       # [B, K] token block
                        nxt = blk if K == 1 else blk[:, -1:]
                    else:
                        ids = ops.concat(toks, axis=1) if len(toks) > 1 \
                            else ids
                        toks = [ids]
                        hidden = self.llama(ids)
                        if self.lm_head is not None:
                            logits = self.lm_head(hidden[:, -1])
                        else:
                            logits = ops.matmul(
                                hidden[:, -1],
                                self.llama.embed_tokens.weight,
                                transpose_y=True)
                        nxt = self._sample(logits, do_sample, top_p, top_k,
                                           temperature, seed)
                    if eos_token_id is not None:
                        import jax.numpy as jnp
                        done_now = Tensor(
                            (nxt._data == eos_token_id).reshape(-1))
                        if finished is not None:
                            nxt = Tensor(jnp.where(
                                finished._data,
                                jnp.asarray(eos_token_id, nxt._data.dtype),
                                nxt._data.reshape(-1)).reshape(-1, 1))
                            done_now = Tensor(finished._data | done_now._data)
                        finished = done_now
                    nxt = nxt.astype(toks[0].dtype)
                    if use_cache and K > 1:
                        toks.append(blk.astype(toks[0].dtype))
                    else:
                        toks.append(nxt)
                    cur = nxt
                    if finished is not None and \
                            bool(np.asarray(finished._data).all()):
                        break
            finally:
                if gen_entry is not None:
                    gen_entry["busy"] = False
            out = ops.concat(toks, axis=1) if len(toks) > 1 else toks[0]
            if use_cache and K > 1:
                out = out[:, :prompt + max_new_tokens]  # trim K overshoot
            return out

    def _sample(self, logits, do_sample, top_p, top_k, temperature, seed):
        from .. import ops
        if not do_sample:
            return ops.argmax(logits, axis=-1, keepdim=True)
        if temperature and temperature != 1.0:
            logits = logits / temperature
        from ..nn import functional as F
        probs = F.softmax(logits, axis=-1)
        if top_k:
            vals, _ = ops.topk(probs, k=top_k)
            import jax.numpy as jnp
            thresh = vals[:, -1:]
            probs = Tensor(jnp.where(probs._data >= thresh._data,
                                     probs._data, 0.0))
            probs = probs / probs.sum(axis=-1, keepdim=True)
        if top_p < 1.0:
            _, ids = ops.top_p_sampling(probs, top_p,
                                        seed=-1 if seed is None else seed)
            return ids
        return ops.multinomial(probs, num_samples=1)

    def forward(self, input_ids, labels=None, position_ids=None):
        hidden = self.llama(input_ids, position_ids)
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = ops.matmul(hidden, self.llama.embed_tokens.weight,
                                transpose_y=True)
        if labels is not None:
            aux = None
            for layer in self.llama.layers:
                al = getattr(layer.mlp, "aux_loss", None)
                if al is not None:
                    aux = al if aux is None else aux + al
            return logits, causal_lm_loss(logits, labels,
                                          self.config.vocab_size, aux)
        return logits


def shard_llama(model: LlamaForCausalLM, mesh, fsdp_axis="dp", mp_axis="mp"):
    """Apply the hybrid placement policy: Megatron TP on 'mp', FSDP (param
    sharding) on the fsdp axis — SURVEY §2.4 DP/sharding/TP mapping."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.mp_layers import _shard_param

    def put(p, spec):
        if p is not None:
            _shard_param(p, spec)

    put(model.llama.embed_tokens.weight, P(mp_axis, None))
    if model.lm_head is not None:
        put(model.lm_head.weight, P(None, mp_axis))
    for layer in model.llama.layers:
        att, mlp = layer.self_attn, layer.mlp
        put(att.q_proj.weight, P(fsdp_axis, mp_axis))
        put(att.k_proj.weight, P(fsdp_axis, mp_axis))
        put(att.v_proj.weight, P(fsdp_axis, mp_axis))
        put(att.o_proj.weight, P(mp_axis, fsdp_axis))
        if isinstance(mlp, LlamaMLP):
            put(mlp.gate_proj.weight, P(fsdp_axis, mp_axis))
            put(mlp.up_proj.weight, P(fsdp_axis, mp_axis))
            put(mlp.down_proj.weight, P(mp_axis, fsdp_axis))
    return model


def causal_lm_loss(logits, labels, vocab_size, aux_loss=None, aux_coef=0.01):
    """Token cross-entropy (+ optional MoE load-balance aux) — the one loss
    formula shared by the dense and pipeline-partitioned models."""
    loss = F.cross_entropy(logits.reshape([-1, vocab_size]),
                           labels.reshape([-1]))
    if aux_loss is not None:
        loss = loss + aux_coef * aux_loss
    return loss


def make_decoder_stage(config: LlamaConfig):
    """Pure-jnp Llama decoder block as (init, apply) — the homogeneous stage
    function for the SPMD stacked-weight pipeline (parallel/pipeline.py), which
    runs inside shard_map on raw arrays. Real block: RMSNorm → GQA attention
    with RoPE → RMSNorm → SwiGLU MLP."""
    import jax

    h = config.hidden_size
    nh, nkv = config.num_attention_heads, config.num_key_value_heads
    hd = h // nh
    m = config.intermediate_size
    theta = config.rope_theta
    eps = config.rms_norm_eps
    std = config.initializer_range

    def init(key):
        ks = jax.random.split(key, 7)
        n = lambda k, shape: jax.random.normal(k, shape, jnp.float32) * std
        return {
            "ln1": jnp.ones((h,), jnp.float32),
            "wq": n(ks[0], (h, nh * hd)), "wk": n(ks[1], (h, nkv * hd)),
            "wv": n(ks[2], (h, nkv * hd)), "wo": n(ks[3], (nh * hd, h)),
            "ln2": jnp.ones((h,), jnp.float32),
            "wg": n(ks[4], (h, m)), "wu": n(ks[5], (h, m)),
            "wd": n(ks[6], (m, h)),
        }

    def _rms(x, w):
        v = jnp.mean(x.astype(jnp.float32) ** 2, -1, keepdims=True)
        return (x * jax.lax.rsqrt(v + eps)).astype(x.dtype) * w

    def _rope(x):
        b, s, n_heads, d = x.shape
        pos = jnp.arange(s, dtype=jnp.float32)
        freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
        ang = pos[:, None] * freqs[None, :]
        cos, sin = jnp.cos(ang), jnp.sin(ang)
        x1, x2 = x[..., ::2], x[..., 1::2]
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
        out = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
        return out.reshape(x.shape)

    def apply(p, x):
        b, s, _ = x.shape
        y = _rms(x, p["ln1"])
        q = _rope((y @ p["wq"]).reshape(b, s, nh, hd))
        k = _rope((y @ p["wk"]).reshape(b, s, nkv, hd))
        v = (y @ p["wv"]).reshape(b, s, nkv, hd)
        if nh != nkv:
            k = jnp.repeat(k, nh // nkv, axis=2)
            v = jnp.repeat(v, nh // nkv, axis=2)
        scores = jnp.einsum("bsnd,btnd->bnst", q, k) / jnp.sqrt(float(hd))
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
        att = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bnst,btnd->bsnd", att, v).reshape(b, s, nh * hd)
        x = x + o @ p["wo"]
        y = _rms(x, p["ln2"])
        return x + (jax.nn.silu(y @ p["wg"]) * (y @ p["wu"])) @ p["wd"]

    return init, apply


class LlamaEmbeddingPipe(Layer):
    """Stage-0 pipe chunk: token embedding (reference PaddleNLP
    LlamaEmbeddingPipe semantics — first pp stage owns the embedding).
    For MoE configs it also seeds the carried aux-loss stream."""

    def __init__(self, config: LlamaConfig, emit_aux=False):
        super().__init__()
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size,
                                      weight_attr=Normal(std=config.initializer_range))
        self._emit_aux = emit_aux

    def forward(self, input_ids):
        h = self.embed_tokens(input_ids)
        if self._emit_aux:
            from ..core.tensor import Tensor
            import jax.numpy as jnp
            return (h, Tensor(jnp.zeros((), jnp.float32)))
        return h


class LlamaDecoderLayerPipe(LlamaDecoderLayer):
    """Decoder chunk that carries the running MoE aux loss through the stage
    boundary as a second stream member — each chunk's aux contribution stays
    inside that chunk's tape segment, so the chunked backward never crosses a
    detach boundary (the reference allreduces aux across the pp group)."""

    def forward(self, x):
        x, aux = x
        h = super().forward(x)
        al = getattr(self.mlp, "aux_loss", None)
        if al is not None:
            aux = aux + al
        return (h, aux)


class LlamaNormHeadPipe(Layer):
    """Last pipe chunk: final RMSNorm + LM head → logits. With tied embeddings
    the weight is read through a closure (not registered here) so it belongs
    to exactly one stage's parameter list."""

    def __init__(self, config: LlamaConfig, tied_weight_getter=None):
        super().__init__()
        self.config = config
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        if config.tie_word_embeddings:
            self.lm_head = None
            self._tied_weight_getter = tied_weight_getter
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=Normal(std=config.initializer_range),
                                  bias_attr=False)

    def forward(self, x):
        aux = None
        if isinstance(x, tuple):
            x, aux = x
        h = self.norm(x)
        if self.lm_head is not None:
            logits = self.lm_head(h)
        else:
            logits = ops.matmul(h, self._tied_weight_getter(), transpose_y=True)
        return logits if aux is None else (logits, aux)


class LlamaForCausalLMPipe:
    """Pipeline-partitioned Llama (reference: PaddleNLP LlamaForCausalLMPipe on
    fleet pp_layers.py:258). Returns a PipelineLayer whose chunks are
    [embedding | decoder blocks … | norm+head], segmented by decoder-layer
    count so embedding rides stage 0 and the head rides the last stage."""

    def __new__(cls, config: LlamaConfig, num_stages=2,
                num_virtual_pipeline_stages=None, recompute_interval=0,
                topology=None):
        from ..parallel.pipeline_layer import PipelineLayer

        moe = config.num_experts > 0
        embed = LlamaEmbeddingPipe(config, emit_aux=moe)
        dec_cls = LlamaDecoderLayerPipe if moe else LlamaDecoderLayer
        decoders = [dec_cls(config) for _ in range(config.num_hidden_layers)]
        head = LlamaNormHeadPipe(
            config, tied_weight_getter=lambda: embed.embed_tokens.weight)

        def loss_fn(out, labels):
            logits, aux = out if isinstance(out, tuple) else (out, None)
            return causal_lm_loss(logits, labels, config.vocab_size, aux)

        pipe = PipelineLayer(
            [embed] + decoders + [head],
            num_stages=num_stages, loss_fn=loss_fn,
            seg_method=f"layer:{dec_cls.__name__}",
            recompute_interval=recompute_interval,
            num_virtual_pipeline_stages=num_virtual_pipeline_stages,
            topology=topology)
        pipe.config = config
        if config.tie_word_embeddings:
            pipe._pin_exempt.add(id(embed.embed_tokens.weight))
        return pipe


def llama3_8b():
    return LlamaForCausalLM(LlamaConfig.llama3_8b())


def llama_tiny():
    return LlamaForCausalLM(LlamaConfig.tiny())


def llama_tiny_moe():
    return LlamaForCausalLM(LlamaConfig.tiny_moe())
