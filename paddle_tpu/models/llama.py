"""Llama-3 family (BASELINE config #2: 8B pretrain, FSDP→GSPMD; #5 MoE variant).

Architecture: RMSNorm + GQA attention with RoPE + SwiGLU MLP, tied to the
paddle_tpu.nn stack. `shard_llama` applies the hybrid placement policy
(dp/fsdp/mp/sep axes) — the fleet 4D mapping from SURVEY §2.4 as GSPMD.
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from .. import ops
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding
from ..nn.layer.norm import RMSNorm
from ..nn.layer.container import LayerList
from ..nn import functional as F
from ..nn.functional.rope import fused_rotary_position_embedding
from ..nn.initializer import Normal


class LlamaConfig:
    def __init__(self, vocab_size=128256, hidden_size=4096, intermediate_size=14336,
                 num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
                 max_position_embeddings=8192, rms_norm_eps=1e-5, rope_theta=500000.0,
                 tie_word_embeddings=False, initializer_range=0.02,
                 num_experts=0, num_experts_per_tok=2, moe_intermediate_size=None):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.tie_word_embeddings = tie_word_embeddings
        self.initializer_range = initializer_range
        self.num_experts = num_experts
        self.num_experts_per_tok = num_experts_per_tok
        self.moe_intermediate_size = moe_intermediate_size

    @classmethod
    def llama3_8b(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        kw.setdefault("vocab_size", 256)
        kw.setdefault("hidden_size", 64)
        kw.setdefault("intermediate_size", 128)
        kw.setdefault("num_hidden_layers", 2)
        kw.setdefault("num_attention_heads", 4)
        kw.setdefault("num_key_value_heads", 2)
        kw.setdefault("max_position_embeddings", 128)
        kw.setdefault("rope_theta", 10000.0)
        return cls(**kw)

    @classmethod
    def tiny_moe(cls, **kw):
        kw.setdefault("num_experts", 4)
        return cls.tiny(**kw)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = h // self.num_heads
        self.rope_theta = config.rope_theta
        init = Normal(std=config.initializer_range)
        self.q_proj = Linear(h, self.num_heads * self.head_dim, weight_attr=init,
                             bias_attr=False)
        self.k_proj = Linear(h, self.num_kv_heads * self.head_dim, weight_attr=init,
                             bias_attr=False)
        self.v_proj = Linear(h, self.num_kv_heads * self.head_dim, weight_attr=init,
                             bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, h, weight_attr=init,
                             bias_attr=False)

    def forward(self, x, position_ids=None):
        b, s, h = x.shape
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        q, k, _ = fused_rotary_position_embedding(
            q, k, None, position_ids=position_ids, rotary_emb_base=self.rope_theta)
        from ..distributed.fleet.topology import get_hybrid_communicate_group
        if get_hybrid_communicate_group().get_sep_parallel_world_size() > 1:
            # context parallelism: sequence sharded on 'sep', ring attention
            from ..parallel.ring_attention import ring_flash_attention
            rep = self.num_heads // self.num_kv_heads
            if rep > 1:
                k = ops.repeat_interleave(k, rep, axis=2)
                v = ops.repeat_interleave(v, rep, axis=2)
            out = ring_flash_attention(q, k, v, causal=True, axis_name="sep")
        else:
            out, _ = F.flash_attention(q, k, v, causal=True)
        return self.o_proj(out.reshape([b, s, self.num_heads * self.head_dim]))


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        init = Normal(std=config.initializer_range)
        self.gate_proj = Linear(h, m, weight_attr=init, bias_attr=False)
        self.up_proj = Linear(h, m, weight_attr=init, bias_attr=False)
        self.down_proj = Linear(m, h, weight_attr=init, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        if config.num_experts > 0:
            from ..parallel.moe import MoELayer
            self.mlp = MoELayer(config.hidden_size, num_experts=config.num_experts,
                                d_hidden=config.moe_intermediate_size
                                or config.intermediate_size)
        else:
            self.mlp = LlamaMLP(config)

    def forward(self, x, position_ids=None):
        x = x + self.self_attn(self.input_layernorm(x), position_ids)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size,
                                      weight_attr=Normal(std=config.initializer_range))
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)

    def forward(self, input_ids, position_ids=None):
        x = self.embed_tokens(input_ids)
        for layer in self.layers:
            x = layer(x, position_ids)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=Normal(std=config.initializer_range),
                                  bias_attr=False)

    def forward(self, input_ids, labels=None, position_ids=None):
        hidden = self.llama(input_ids, position_ids)
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = ops.matmul(hidden, self.llama.embed_tokens.weight,
                                transpose_y=True)
        if labels is not None:
            loss = F.cross_entropy(logits.reshape([-1, self.config.vocab_size]),
                                   labels.reshape([-1]))
            aux = None
            for layer in self.llama.layers:
                al = getattr(layer.mlp, "aux_loss", None)
                if al is not None:
                    aux = al if aux is None else aux + al
            if aux is not None:
                loss = loss + 0.01 * aux
            return logits, loss
        return logits


def shard_llama(model: LlamaForCausalLM, mesh, fsdp_axis="dp", mp_axis="mp"):
    """Apply the hybrid placement policy: Megatron TP on 'mp', FSDP (param
    sharding) on the fsdp axis — SURVEY §2.4 DP/sharding/TP mapping."""
    from jax.sharding import PartitionSpec as P
    from ..parallel.mp_layers import _shard_param

    def put(p, spec):
        if p is not None:
            _shard_param(p, spec)

    put(model.llama.embed_tokens.weight, P(mp_axis, None))
    if model.lm_head is not None:
        put(model.lm_head.weight, P(None, mp_axis))
    for layer in model.llama.layers:
        att, mlp = layer.self_attn, layer.mlp
        put(att.q_proj.weight, P(fsdp_axis, mp_axis))
        put(att.k_proj.weight, P(fsdp_axis, mp_axis))
        put(att.v_proj.weight, P(fsdp_axis, mp_axis))
        put(att.o_proj.weight, P(mp_axis, fsdp_axis))
        if isinstance(mlp, LlamaMLP):
            put(mlp.gate_proj.weight, P(fsdp_axis, mp_axis))
            put(mlp.up_proj.weight, P(fsdp_axis, mp_axis))
            put(mlp.down_proj.weight, P(mp_axis, fsdp_axis))
    return model


def llama3_8b():
    return LlamaForCausalLM(LlamaConfig.llama3_8b())


def llama_tiny():
    return LlamaForCausalLM(LlamaConfig.tiny())


def llama_tiny_moe():
    return LlamaForCausalLM(LlamaConfig.tiny_moe())
