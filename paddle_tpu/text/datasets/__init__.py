"""paddle.text.datasets analog (reference: python/paddle/text/datasets —
Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16, Conll05st; all
download-then-parse).

No egress in this environment: each dataset parses reference-format files
from a local `data_file` path and raises with instructions when absent."""
from __future__ import annotations

import os
import re
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Conll05st", "Movielens",
           "WMT14", "WMT16"]


def _require(path, name, url):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"{name}: dataset file not found at {path!r} and this "
            f"environment cannot download ({url}). Pass data_file= pointing "
            f"at the reference-format archive.")


class UCIHousing(Dataset):
    """506x14 whitespace table -> (13 features, 1 target) float32
    (reference: text/datasets/uci_housing.py)."""

    def __init__(self, data_file=None, mode="train", download=True):
        _require(data_file, "UCIHousing", "uci housing data url")
        raw = np.loadtxt(data_file).astype(np.float32)
        feat = raw[:, :-1]
        mn, mx = feat.min(0), feat.max(0)
        feat = (feat - feat.mean(0)) / np.maximum(mx - mn, 1e-9)
        raw = np.concatenate([feat, raw[:, -1:]], 1)
        cut = int(len(raw) * 0.8)
        self.data = raw[:cut] if mode == "train" else raw[cut:]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i, :-1], self.data[i, -1:]


class Imdb(Dataset):
    """IMDB sentiment from aclImdb tar (reference: text/datasets/imdb.py)."""

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 download=True):
        _require(data_file, "Imdb", "aclImdb_v1.tar.gz")
        # vocabulary over the WHOLE corpus (train+test) so both modes share
        # word ids (reference builds one word dict, imdb.py word_dict)
        pat_mode = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        pat_any = re.compile(r"aclImdb/(train|test)/(pos|neg)/.*\.txt$")
        docs, labels = [], []
        freq = {}
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if not pat_any.match(m.name):
                    continue
                text = tf.extractfile(m).read().decode("latin-1").lower()
                toks = re.findall(r"[a-z]+", text)
                for t in toks:
                    freq[t] = freq.get(t, 0) + 1
                if pat_mode.match(m.name):
                    docs.append(toks)
                    labels.append(0 if "/pos/" in m.name else 1)
        vocab = [w for w, c in sorted(freq.items(), key=lambda kv: (-kv[1],
                                                                    kv[0]))
                 if c > cutoff]
        self.word_idx = {w: i for i, w in enumerate(vocab)}
        self.word_idx["<unk>"] = len(self.word_idx)
        unk = self.word_idx["<unk>"]
        self.docs = [np.array([self.word_idx.get(t, unk) for t in d],
                              np.int64) for d in docs]
        self.labels = np.array(labels, np.int64)

    def __len__(self):
        return len(self.docs)

    def __getitem__(self, i):
        return self.docs[i], self.labels[i]


class Imikolov(Dataset):
    """PTB n-gram dataset (reference: text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, download=True):
        _require(data_file, "Imikolov", "simple-examples.tgz")
        fname = f"./simple-examples/data/ptb.{mode}.txt"
        freq = {}
        lines = []
        with tarfile.open(data_file) as tf:
            for m in tf.getmembers():
                if m.name.lstrip("./") == fname.lstrip("./"):
                    for ln in tf.extractfile(m).read().decode().splitlines():
                        toks = ln.strip().split()
                        lines.append(toks)
                        for t in toks:
                            freq[t] = freq.get(t, 0) + 1
        if not lines:
            raise ValueError(
                f"Imikolov: no member './simple-examples/data/ptb.{mode}"
                f".txt' found in {data_file!r} — wrong archive layout?")
        vocab = [w for w, c in freq.items() if c >= min_word_freq]
        self.word_idx = {w: i for i, w in enumerate(sorted(vocab))}
        unk = len(self.word_idx)
        self.word_idx["<unk>"] = unk
        self.data = []
        for toks in lines:
            ids = [self.word_idx.get(t, unk) for t in toks]
            if data_type.upper() == "NGRAM":
                for i in range(len(ids) - window_size + 1):
                    self.data.append(np.array(ids[i:i + window_size],
                                              np.int64))
            else:
                self.data.append(np.array(ids, np.int64))

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


class _GatedDataset(Dataset):
    """Datasets whose archives aren't present in this environment; loading
    raises with the reference URL so the API surface still exists."""

    _URL = ""

    def __init__(self, data_file=None, mode="train", download=True, **kw):
        _require(data_file, type(self).__name__, self._URL)
        raise NotImplementedError(
            f"{type(self).__name__}: parser for local archives lands with "
            f"file-format fixtures; see reference text/datasets.")


class Conll05st(_GatedDataset):
    _URL = "conll05st-tests.tar.gz"


class Movielens(_GatedDataset):
    _URL = "ml-1m.zip"


class WMT14(_GatedDataset):
    _URL = "wmt14.tgz"


class WMT16(_GatedDataset):
    _URL = "wmt16.tar.gz"
