"""paddle.text analog (reference: python/paddle/text — viterbi_decode.py +
datasets/).

TPU-native: Viterbi is a lax.scan over time with a dense [T, B, N] potential
tensor — max-product forward pass + backtrace, one compiled program, no
per-step host sync (the reference runs a phi viterbi_decode kernel)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from ..nn.layer.layers import Layer
from . import datasets

__all__ = ["viterbi_decode", "ViterbiDecoder", "datasets"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag=True, name=None):
    """reference: text/viterbi_decode.py:31.

    potentials [B, T, N], transition_params [N, N], lengths [B] ->
    (scores [B], paths [B, T] int64; positions past each length are 0)."""
    def f(emit, trans, lens):
        B, T, N = emit.shape
        e = jnp.moveaxis(emit.astype(jnp.float32), 1, 0)     # [T, B, N]
        tr = trans.astype(jnp.float32)
        if include_bos_eos_tag:
            # last row/col = BOS, second-to-last = EOS (reference contract)
            alpha0 = e[0] + tr[-1][None, :]
        else:
            alpha0 = e[0]
        steps = jnp.arange(1, T)

        def body(alpha, inp):
            et, t = inp
            # alpha [B, N]; score of moving i->j
            m = alpha[:, :, None] + tr[None, :, :]           # [B, N, N]
            best = jnp.max(m, axis=1) + et                   # [B, N]
            idx = jnp.argmax(m, axis=1)                      # [B, N]
            # sequences already past their length keep alpha frozen
            active = (t < lens)[:, None]
            return jnp.where(active, best, alpha), idx

        alphaT, backptrs = jax.lax.scan(body, alpha0, (e[1:], steps))
        if include_bos_eos_tag:
            # transition into EOS for each sequence's final state
            alphaT = alphaT + tr[:, -2][None, :]
        scores = jnp.max(alphaT, axis=-1)
        last = jnp.argmax(alphaT, axis=-1)                   # [B]

        # backtrace from each sequence's last valid position
        def back(carry, inp):
            tag, t = carry, inp[0]
            ptr = inp[1]                                     # [B, N]
            prev = jnp.take_along_axis(ptr, tag[:, None], 1)[:, 0]
            active = (t < lens)
            tag2 = jnp.where(active, prev, tag)
            return tag2, tag

        rev_steps = jnp.arange(T - 1, 0, -1)
        rev_ptrs = backptrs[::-1]
        tag0, tags_rev = jax.lax.scan(back, last, (rev_steps, rev_ptrs))
        path = jnp.concatenate([tag0[None, :], tags_rev[::-1]], 0)  # [T, B]
        path = jnp.moveaxis(path, 0, 1)                      # [B, T]
        mask = jnp.arange(T)[None, :] < lens[:, None]
        return scores, jnp.where(mask, path, 0).astype(jnp.int64)

    return apply_op("viterbi_decode", f, potentials, transition_params,
                    Tensor(jnp.asarray(unwrap(lengths)).astype(jnp.int32)))


class ViterbiDecoder(Layer):
    """reference: viterbi_decode.py:110 — holds the transition matrix."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions if isinstance(transitions, Tensor) \
            else Tensor(jnp.asarray(np.asarray(transitions), jnp.float32))
        self._include = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self._include)
from .datasets import (UCIHousing, Imdb, Imikolov, Conll05st, Movielens,  # noqa: F401,E402
                       WMT14, WMT16)
