"""paddle.signal analog — STFT/ISTFT (reference: python/paddle/signal.py over
phi frame/overlap_add kernels). Framing is a gather (static indices, so XLA
lowers it to cheap dynamic-slices); overlap-add is a segment-sum scatter."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .core.dispatch import apply_op, unwrap
from .core.tensor import Tensor

__all__ = ["stft", "istft", "frame", "overlap_add"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice x into overlapping frames along `axis` (reference signal.frame)."""
    def f(a):
        n = a.shape[axis]
        n_frames = 1 + (n - frame_length) // hop_length
        starts = np.arange(n_frames) * hop_length
        idx = starts[:, None] + np.arange(frame_length)[None, :]
        moved = jnp.moveaxis(a, axis, -1)
        framed = moved[..., jnp.asarray(idx)]        # [..., n_frames, frame_length]
        if axis in (-1, a.ndim - 1):
            return jnp.moveaxis(framed, (-2, -1), (-1, -2))  # [.., frame_length, n_frames]
        return jnp.moveaxis(framed, (-2, -1), (axis, axis + 1))
    return apply_op("frame", f, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Reconstruct from frames by overlap-adding (reference signal.overlap_add).
    x: [..., frame_length, n_frames] when axis=-1."""
    def f(a):
        if axis in (-1, a.ndim - 1):
            fl, nf = a.shape[-2], a.shape[-1]
            frames = jnp.moveaxis(a, -1, -2)          # [..., n_frames, frame_length]
        else:
            fl, nf = a.shape[axis + 1], a.shape[axis]
            frames = jnp.moveaxis(a, (axis, axis + 1), (-2, -1))
        n = (nf - 1) * hop_length + fl
        starts = np.arange(nf) * hop_length
        idx = (starts[:, None] + np.arange(fl)[None, :]).reshape(-1)
        flat = frames.reshape(frames.shape[:-2] + (nf * fl,))
        out = jnp.zeros(frames.shape[:-2] + (n,), a.dtype)
        return out.at[..., jnp.asarray(idx)].add(flat)
    return apply_op("overlap_add", f, x)


def stft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
         pad_mode="reflect", normalized=False, onesided=True, name=None):
    """Short-time Fourier transform (reference python/paddle/signal.py:stft).
    x: [batch, n] or [n] real (or complex with onesided=False).
    Returns [batch, n_fft//2+1 | n_fft, n_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    w = unwrap(window) if isinstance(window, Tensor) else window

    def f(a, *rest):
        win = rest[0] if rest else jnp.ones((win_length,), jnp.float32)
        # pad window to n_fft centered
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        if center:
            pad = [(0, 0)] * (a.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            a = jnp.pad(a, pad, mode=pad_mode)
        n = a.shape[-1]
        n_frames = 1 + (n - n_fft) // hop_length
        starts = np.arange(n_frames) * hop_length
        idx = starts[:, None] + np.arange(n_fft)[None, :]
        frames = a[..., jnp.asarray(idx)] * win       # [..., n_frames, n_fft]
        if onesided and not jnp.iscomplexobj(a):
            spec = jnp.fft.rfft(frames, axis=-1)
        else:
            spec = jnp.fft.fft(frames, axis=-1)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.moveaxis(spec, -1, -2)             # [..., freq, n_frames]

    args = (x, window) if isinstance(window, Tensor) else (x,)
    return apply_op("stft", f, *args)


def istft(x, n_fft, hop_length=None, win_length=None, window=None, center=True,
          normalized=False, onesided=True, length=None, return_complex=False,
          name=None):
    """Inverse STFT with windowed overlap-add + window-envelope normalization
    (reference signal.istft)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def f(a, *rest):
        win = rest[0] if rest else jnp.ones((win_length,), jnp.float32)
        if win_length < n_fft:
            lp = (n_fft - win_length) // 2
            win = jnp.pad(win, (lp, n_fft - win_length - lp))
        spec = jnp.moveaxis(a, -2, -1)                # [..., n_frames, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
        if onesided:
            frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
        else:
            frames = jnp.fft.ifft(spec, axis=-1)
            if not return_complex:
                frames = frames.real
        frames = frames * win
        nf = frames.shape[-2]
        n = (nf - 1) * hop_length + n_fft
        starts = np.arange(nf) * hop_length
        idx = (starts[:, None] + np.arange(n_fft)[None, :]).reshape(-1)
        flat = frames.reshape(frames.shape[:-2] + (nf * n_fft,))
        out = jnp.zeros(frames.shape[:-2] + (n,), flat.dtype)
        out = out.at[..., jnp.asarray(idx)].add(flat)
        # window envelope for COLA normalization
        wsq = jnp.tile(win * win, (nf,))
        env = jnp.zeros((n,), win.dtype).at[jnp.asarray(idx)].add(wsq)
        out = out / jnp.where(env > 1e-11, env, 1.0)
        if center:
            out = out[..., n_fft // 2: n - n_fft // 2]
        if length is not None:
            out = out[..., :length]
        return out

    args = (x, window) if isinstance(window, Tensor) else (x,)
    return apply_op("istft", f, *args)
