"""Reverse-mode engine over the GradNode tape.

Analog of egr::Backward / RunBackward (fluid/eager/backward.cc:439,:105): dependency-
counted topological sweep from the root tensors, accumulating cotangents per tensor,
firing hooks, and writing `.grad` on leaves (and on tensors with retain_grads()).
Runs identically on concrete arrays and under program capture.
"""
from __future__ import annotations

from collections import defaultdict

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .node import GradNode


def _ones_like(t: Tensor):
    return jnp.ones(t._data.shape, dtype=t._data.dtype)


def _is_float0(x) -> bool:
    return getattr(x, "dtype", None) == jax.dtypes.float0


def _same_device(a, b):
    """Move b onto a's device when both are concrete arrays committed to
    different single devices (pp: a stage-shared param — e.g. tied embeddings —
    receives grads from stages pinned to different devices)."""
    try:
        da, db = a.device, b.device
    except Exception:
        return b
    if da is not None and db is not None and da != db:
        return jax.device_put(b, da)
    return b


def _vjp_on_tape(node, out_cots):
    """Run node's vjp through dispatch so the grad computation is recorded
    (double grad). Returns per-input cotangents aligned with node.inputs."""
    from ..core.dispatch import apply_op

    n_in = len(node.in_arrays)
    idxs = [i for i, inp in enumerate(node.inputs)
            if inp is not None and not inp.stop_gradient]
    if not idxs:
        return (None,) * n_in
    raw_fn = node.raw_fn
    n_outs = node.n_outs

    def grad_fn(*xs):
        ins, cots = xs[:n_in], xs[n_in:]
        _, vjp = jax.vjp(raw_fn, *ins)
        arg = cots[0] if n_outs == 1 else tuple(cots)
        all_cots = vjp(arg)
        sel = tuple(all_cots[i] for i in idxs)
        return sel if len(sel) > 1 else sel[0]

    args = [node.inputs[i] if node.inputs[i] is not None else node.in_arrays[i]
            for i in range(n_in)]
    res = apply_op(f"grad[{node.name}]", grad_fn, *args, *out_cots)
    res = res if isinstance(res, tuple) else (res,)
    out = [None] * n_in
    for k, i in enumerate(idxs):
        out[i] = res[k]
    return tuple(out)


def backward(tensors, grad_tensors=None, retain_graph=False,
             create_graph=False, _only=None, defer_param_ids=None):
    """paddle.autograd.backward analog.

    create_graph=True runs every node's vjp THROUGH dispatch (apply_op), so
    cotangents are tape Tensors and the produced grads are differentiable —
    the eager double-grad semantics of fluid/eager RunBackward+grad ops.
    _only (internal, paddle.grad only_inputs=True): restrict .grad writes to
    this id-set so a grad() call never pollutes other leaves' .grad.

    Under a break-stitched echo pass (jit/to_static.py) backward is a no-op:
    the compiled program already produced every grad; the echo's placeholder
    tensors carry no tape.

    defer_param_ids (internal, zero-bubble pipeline): id-set of leaf
    parameters whose weight-grad computation is DEFERRED — the sweep
    propagates activation cotangents now (the "B" pass) and returns a list of
    zero-arg "W" closures computing/accumulating the parameter grads; the last
    entry flushes hooks + .grad writes on the per-param summed cotangent.
    For a node with both activation and parameter inputs we re-linearize
    restricted to the activation inputs, so only dX is computed now; the W
    closure re-linearizes restricted to the params. Eagerly that replays the
    node's forward once per phase; under `to_static` capture both
    linearizations land in one XLA module and the duplicated forward
    subexpressions are CSE'd (reference analog: pipeline_zero_bubble.py splits
    matmul_grad into dX-now / dW-later at the op level)."""
    from ..core.dispatch import _state
    tc = _state.trace_ctx
    if tc is not None and getattr(tc, "mode", None) == "echo":
        return [] if defer_param_ids is not None else None
    if create_graph and defer_param_ids:
        raise ValueError("defer_param_ids cannot be combined with create_graph")
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)

    def _const(arr):
        return Tensor(arr, stop_gradient=True) if create_graph else arr

    roots, root_cots = [], []
    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._grad_node is None:
            continue
        roots.append(t)
        if g is None:
            root_cots.append(_const(_ones_like(t)))
        elif isinstance(g, Tensor):
            root_cots.append(g if create_graph else g._data)
        else:
            root_cots.append(_const(jnp.asarray(g)))
    if not roots:
        return [] if defer_param_ids is not None else None

    # --- discover reachable subgraph & count consumer edges per node ---------
    dep = defaultdict(int)     # producer node -> #pending consumer edges
    seen = set()
    stack = [t._grad_node for t in roots if t._grad_node is not None]
    nodes = []
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        nodes.append(node)
        for inp in node.inputs:
            if inp is not None and inp._grad_node is not None:
                dep[id(inp._grad_node)] += 1
                stack.append(inp._grad_node)
    node_by_id = {id(n): n for n in nodes}

    # --- cotangent accumulators keyed by tensor identity ----------------------
    cots: dict[int, object] = {}
    keepalive: dict[int, Tensor] = {}

    def accum_tensor(t: Tensor, cot):
        if _is_float0(cot):
            return
        k = id(t)
        if k in cots:
            from ..core.selected_rows import SelectedRows
            prev = cots[k]
            if isinstance(prev, SelectedRows) or isinstance(cot, SelectedRows):
                # row-sparse cotangent: SR+SR concatenates; mixed densifies
                cots[k] = prev + cot if isinstance(prev, SelectedRows) \
                    else cot + prev
            else:
                cots[k] = prev + _same_device(prev, cot)
        else:
            cots[k] = cot
            keepalive[k] = t

    for t, c in zip(roots, root_cots):
        accum_tensor(t, c)

    def finalize(t: Tensor):
        """Apply hooks; write .grad for leaves / retain_grad tensors."""
        cot = cots.get(id(t))
        if cot is None:
            return None
        from ..core.selected_rows import SelectedRows
        if isinstance(cot, SelectedRows):
            # leaf row-sparse grad: .grad IS the SelectedRows (reference
            # embedding sparse grads). Hooks see the densified view and the
            # cotangent continues DENSE (falls through to the generic path);
            # without hooks, honor the _only filter like the dense path.
            if t._hooks:
                cot = cot.to_dense()
                cots[id(t)] = cot
            else:
                if _only is not None and id(t) not in _only \
                        and not t._retain_grad:
                    return cot
                if (t._grad_node is None and not t.stop_gradient) \
                        or t._retain_grad:
                    if t.grad is None:
                        t.grad = cot
                    elif isinstance(t.grad, SelectedRows):
                        t.grad = t.grad + cot
                    else:            # dense existing grad: densify-add
                        t.grad = Tensor(cot + t.grad, stop_gradient=True)
                return cot
        if t._hooks:
            g = cot if isinstance(cot, Tensor) else Tensor(cot,
                                                           stop_gradient=True)
            for hook in list(t._hooks):
                out = hook(g)
                if out is not None:
                    g = out if isinstance(out, Tensor) else Tensor(jnp.asarray(out))
            cot = g if create_graph else g._data
            cots[id(t)] = cot
        is_leaf = t._grad_node is None
        if _only is not None and id(t) not in _only and not t._retain_grad:
            return cot
        if (is_leaf and not t.stop_gradient) or t._retain_grad:
            if create_graph:
                gt = cot if isinstance(cot, Tensor) else Tensor(cot)
                t.grad = gt if t.grad is None else t.grad + gt
            elif t.grad is None:
                t.grad = Tensor(cot, stop_gradient=True)
            else:
                t.grad = Tensor(t.grad._data + _same_device(t.grad._data, cot),
                                stop_gradient=True)
        return cot

    # --- deferred W machinery (zero-bubble) -----------------------------------
    deferred = []
    pending_w = {}     # id(param) -> [tensor, summed cotangent]

    def _w_accum(t: Tensor, cot):
        if _is_float0(cot):
            return
        e = pending_w.get(id(t))
        if e is None:
            pending_w[id(t)] = [t, cot]
        else:
            e[1] = e[1] + _same_device(e[1], cot)

    def make_w_closure(raw_fn, in_arrays, p_idxs, p_tensors, out_cots, n_outs):
        def w_fn():
            def pf(*ps):
                ins = list(in_arrays)
                for k, i in enumerate(p_idxs):
                    ins[i] = ps[k]
                return raw_fn(*ins)
            _, vjp = jax.vjp(pf, *(in_arrays[i] for i in p_idxs))
            arg = out_cots[0] if n_outs == 1 else tuple(out_cots)
            for t, c in zip(p_tensors, vjp(arg)):
                _w_accum(t, c)
        return w_fn

    def flush_w():
        """Hooks fire once on the per-param summed cotangent, matching the
        joint sweep's finalize semantics."""
        for t, cot in pending_w.values():
            if t._hooks:
                g = Tensor(cot, stop_gradient=True)
                for hook in list(t._hooks):
                    out = hook(g)
                    if out is not None:
                        g = out if isinstance(out, Tensor) else \
                            Tensor(jnp.asarray(out))
                cot = g._data
            if t.grad is None:
                t.grad = Tensor(cot, stop_gradient=True)
            else:
                t.grad = Tensor(t.grad._data + _same_device(t.grad._data, cot),
                                stop_gradient=True)
        pending_w.clear()

    # --- seed ready queue: nodes with no pending consumers --------------------
    ready = [n for n in nodes if dep[id(n)] == 0]
    processed = set()
    while ready:
        node = ready.pop()
        if id(node) in processed:
            continue
        processed.add(id(node))
        if node.freed or (node.vjp_fn is None and not node.deferred):
            raise RuntimeError(
                f"grad graph for node '{node.name}' was already freed; "
                "pass retain_graph=True to backward() to backprop twice.")
        # collect output cotangents (zeros for unused outputs); a non-leaf
        # output's accumulated cotangent is fully consumed here, so drop it
        # from the accumulator to keep backward peak memory at the frontier
        out_cots = []
        for i, ref in enumerate(node.out_refs):
            t = ref() if ref is not None else None
            cot = None
            if t is not None:
                cot = finalize(t)
                if (cot is not None and t._grad_node is not None
                        and not t._retain_grad):
                    cots.pop(id(t), None)
                    keepalive.pop(id(t), None)
            if cot is None:
                shape, dt = node.out_avals[i]
                cot = _const(jnp.zeros(shape, dtype=dt))
            out_cots.append(cot)
        # classify inputs for the zero-bubble split: deferred leaf params vs
        # activations that must propagate now
        p_idxs, a_idxs = [], []
        if defer_param_ids:
            for i, inp in enumerate(node.inputs):
                if inp is None or inp.stop_gradient:
                    continue
                if id(inp) in defer_param_ids and inp._grad_node is None:
                    p_idxs.append(i)
                else:
                    a_idxs.append(i)
        splittable = (bool(p_idxs) and node.raw_fn is not None
                      and node.in_arrays is not None)
        if splittable:
            raw_fn, in_arrays = node.raw_fn, node.in_arrays
            deferred.append(make_w_closure(
                raw_fn, in_arrays, tuple(p_idxs),
                tuple(node.inputs[i] for i in p_idxs),
                tuple(out_cots), node.n_outs))
            in_cots = [None] * len(node.inputs)
            if a_idxs:
                def af(*acts, _ia=in_arrays, _ai=tuple(a_idxs), _fn=raw_fn):
                    ins = list(_ia)
                    for k, i in enumerate(_ai):
                        ins[i] = acts[k]
                    return _fn(*ins)
                _, avjp = jax.vjp(af, *(in_arrays[i] for i in a_idxs))
                arg = out_cots[0] if node.n_outs == 1 else tuple(out_cots)
                acots = avjp(arg)
                for k, i in enumerate(a_idxs):
                    in_cots[i] = acots[k]
        elif create_graph and node.raw_fn is not None:
            in_cots = _vjp_on_tape(node, out_cots)
        else:
            arg = out_cots[0] if node.n_outs == 1 else tuple(out_cots)
            if create_graph:
                arg = jax.tree_util.tree_map(
                    lambda c: c._data if isinstance(c, Tensor) else c, arg)
            in_cots = node.pullback(arg)
        del out_cots
        if not retain_graph and not create_graph:
            node.release()
        for inp, cot in zip(node.inputs, in_cots):
            if inp is None or inp.stop_gradient or cot is None:
                continue
            accum_tensor(inp, cot)
            prod = inp._grad_node
            if prod is not None:
                dep[id(prod)] -= 1
                if dep[id(prod)] == 0:
                    ready.append(node_by_id[id(prod)])
        if not retain_graph and not create_graph and not node.keep_arrays:
            # drop the node's strong refs to its input tensors so forward
            # activations free progressively as the sweep walks the tape
            # (keep_arrays = a static.program_guard recorder still needs the
            # graph for Executor.run replay)
            node.inputs = (None,) * len(node.inputs)
    # finalize leaves that never went through a node's out_refs; params whose
    # grads were deferred never entered `cots`, so this flushes only the
    # immediately-computed cotangents
    for k, t in list(keepalive.items()):
        if t._grad_node is None:
            finalize(t)
    if defer_param_ids is not None:
        if deferred:
            deferred.append(flush_w)
        return deferred


def backward_split(tensors, grad_tensors=None, param_ids=frozenset()):
    """Zero-bubble B-phase backward: propagate activation cotangents now,
    return deferred W closures for the leaf params in `param_ids` (last entry
    flushes hooks + .grad writes). Thin wrapper over backward(); see its
    defer_param_ids docs."""
    return backward(tensors, grad_tensors, defer_param_ids=param_ids)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None, create_graph=False,
         only_inputs=True, allow_unused=False, no_grad_vars=None):
    """paddle.grad analog (python/paddle/autograd/__init__.py).

    create_graph=True returns differentiable grads: the backward sweep's vjp
    calls run through dispatch, so grad-of-grad (and higher) just works —
    see _vjp_on_tape (reference: fluid/eager double-grad node recording)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]

    # run a private sweep: temporarily mark inputs retain_grad, snapshot .grad
    snap = [(t.grad, t._retain_grad) for t in inputs]
    for t in inputs:
        t.grad = None
        t._retain_grad = True
    try:
        backward(list(outputs), grad_outputs,
                 retain_graph=bool(retain_graph) or create_graph,
                 create_graph=create_graph,
                 _only={id(t) for t in inputs} if only_inputs else None)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(
                        "one of the input tensors received no gradient; pass "
                        "allow_unused=True to get None instead")
                results.append(None)
            else:
                results.append(t.grad)
        return results
    finally:
        for t, (g, r) in zip(inputs, snap):
            t.grad, t._retain_grad = g, r
