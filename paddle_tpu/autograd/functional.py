"""Functional differentiation API — jacobian / hessian (reference:
python/paddle/autograd/autograd.py Jacobian:L~30, Hessian, exported via
python/paddle/autograd/__init__.py:26).

Tape-native: rows are computed with `grad(create_graph=...)` sweeps over the
recorded graph, so jacobian composes with the rest of eager autograd (and
hessian is literally jacobian-of-jacobian). Under `to_static` capture the row
sweeps trace into one XLA program like any other eager code.
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor
from .backward import grad as _grad


def _flat_size(t: Tensor, batch_axis):
    shape = list(t.shape)
    if batch_axis is not None:
        shape.pop(batch_axis)
    return int(np.prod(shape)) if shape else 1


def _row_grad(y_elem, xs, create_graph):
    return _grad([y_elem], xs, retain_graph=True, create_graph=create_graph,
                 allow_unused=True)


def jacobian(ys, xs, batch_axis=None, create_graph=False):
    """J[i, j] = d ys_flat[i] / d xs_flat[j].

    ys, xs: Tensor or list of Tensors. With batch_axis=0 (the only supported
    batch axis, matching the reference), ys/xs are [B, *] and the result is
    [B, ny, nx] — batch elements are assumed independent (the reference's
    contract). Returns a Tensor for single ys/xs, nested lists otherwise."""
    single_y = isinstance(ys, Tensor)
    single_x = isinstance(xs, Tensor)
    ys_l = [ys] if single_y else list(ys)
    xs_l = [xs] if single_x else list(xs)
    if batch_axis not in (None, 0):
        raise ValueError("batch_axis must be None or 0")

    from .. import ops

    out_rows = []
    for y in ys_l:
        ny = _flat_size(y, batch_axis)
        if batch_axis is None:
            y_flat = y.reshape([-1])
        else:
            y_flat = y.reshape([y.shape[0], -1])
        rows = []       # rows[i] = tuple over xs of grad arrays
        for i in range(ny):
            y_i = y_flat[i] if batch_axis is None else y_flat[:, i].sum()
            gs = _row_grad(y_i, xs_l, create_graph)
            row = []
            for x, g in zip(xs_l, gs):
                if g is None:
                    g = ops.zeros_like(x)
                if batch_axis is None:
                    row.append(g.reshape([-1]))
                else:
                    row.append(g.reshape([g.shape[0], -1]))
            rows.append(row)
        per_x = []
        for k, x in enumerate(xs_l):
            stacked = ops.stack([r[k] for r in rows],
                                axis=0 if batch_axis is None else 1)
            per_x.append(stacked)   # [ny, nx] or [B, ny, nx]
        out_rows.append(per_x)

    if single_y and single_x:
        return out_rows[0][0]
    if single_y:
        return out_rows[0]
    if single_x:
        return [r[0] for r in out_rows]
    return out_rows


def hessian(ys, xs, batch_axis=None):
    """H[i, j] = d^2 ys / d xs_i d xs_j for scalar ys (per batch element when
    batch_axis=0). Implemented as jacobian of a create_graph jacobian."""
    single_x = isinstance(xs, Tensor)
    xs_l = [xs] if single_x else list(xs)
    if not isinstance(ys, Tensor):
        raise TypeError("hessian expects a single (scalar) output tensor")
    n_scalar = _flat_size(ys, batch_axis)
    if n_scalar != 1:
        raise ValueError("hessian needs a scalar ys (per batch element)")
    first = jacobian(ys, xs_l, batch_axis=batch_axis, create_graph=True)
    # first[i] is [1, nx_i] ([B, 1, nx_i] batched); flattening inside the
    # second jacobian makes block H[i][j] = [nx_i, nx_j] ([B, nx_i, nx_j])
    out = [jacobian(g, xs_l, batch_axis=batch_axis) for g in first]
    if single_x:
        return out[0][0]
    return out
