"""Autograd tape node.

Analog of GradNodeBase (fluid/eager/grad_node_info.h:197): produced by dispatch when
an op runs with grad-requiring inputs. `vjp_fn` is the jax.vjp pullback closing over
residuals (the saved-tensor analog — immutable, so no inplace-version checks needed).
"""
from __future__ import annotations

import weakref

import numpy as np
import jax

from ..core.tensor import Tensor


class GradNode:
    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "out_refs", "n_outs",
                 "raw_fn", "in_arrays", "deferred", "freed", "keep_arrays")

    def __init__(self, name, vjp_fn, inputs, out_arrays, raw_fn=None,
                 in_arrays=None, deferred=False, keep_arrays=False):
        self.name = name
        self.vjp_fn = vjp_fn
        # keep only Tensor inputs' autograd linkage; raw arrays get None
        self.inputs = tuple(i if isinstance(i, Tensor) else None for i in inputs)
        self.out_avals = tuple((o.shape, np.dtype(o.dtype)) for o in out_arrays)
        self.n_outs = len(out_arrays)
        self.out_refs = ()
        # for create_graph (double grad): re-run the vjp THROUGH dispatch so
        # the grad computation itself lands on the tape (fluid/eager double
        # grad records grad ops the same way)
        self.raw_fn = raw_fn
        self.in_arrays = in_arrays
        # deferred: vjp_fn is None by design — backward recomputes it from
        # raw_fn+in_arrays (memory-light capture spy / recompute-grad mode)
        self.deferred = deferred
        self.freed = False
        # static.program_guard replay needs raw_fn/in_arrays after backward
        self.keep_arrays = keep_arrays

    def set_outputs(self, tensors):
        self.out_refs = tuple(weakref.ref(t) for t in tensors)

    def pullback(self, arg):
        """Output-cotangents -> input-cotangents. Deferred nodes recompute the
        vjp here and drop the residuals immediately after."""
        if self.vjp_fn is not None:
            return self.vjp_fn(arg)
        _, vjp_fn = jax.vjp(self.raw_fn, *self.in_arrays)
        try:
            return vjp_fn(arg)
        finally:
            del vjp_fn

    def release(self):
        """Free grad resources after the sweep consumed this node. Keeps the
        graph structure (inputs/avals) but drops residuals; also drops the
        recompute closure unless a static replay recorder needs it."""
        self.vjp_fn = None
        self.freed = True
        if not self.keep_arrays:
            self.raw_fn = None
            self.in_arrays = None

    def __repr__(self):
        return f"GradNode({self.name}, n_outs={self.n_outs})"
