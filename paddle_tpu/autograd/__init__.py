"""Autograd public API (reference: python/paddle/autograd/__init__.py)."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..core.dispatch import _state, set_grad_enabled as _set, grad_enabled
from ..core.tensor import Tensor
from .backward import backward, grad
from .functional import jacobian, hessian  # noqa: F401
from .node import GradNode


class no_grad:
    """Context manager & decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _set(False)
        return self

    def __exit__(self, *exc):
        _set(self._prev)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)
        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = _set(True)
        return self

    def __exit__(self, *exc):
        _set(self._prev)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with enable_grad():
                return fn(*args, **kwargs)
        return wrapper


class set_grad_enabled:
    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = _set(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _set(self._prev)
        return False


def is_grad_enabled() -> bool:
    return grad_enabled()


# ---- PyLayer -----------------------------------------------------------------
class PyLayerContext:
    """ctx passed to PyLayer.forward/backward (python/paddle/autograd/py_layer.py:36)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, ns):
        super().__init__(name, bases, ns)


class PyLayer(metaclass=PyLayerMeta):
    """User-defined differentiable function with explicit forward/backward.

    Implemented over jax.custom_vjp semantics but on the eager tape: forward runs
    under no_grad; a synthetic GradNode calls the user's backward.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        needs_grad = grad_enabled() and any(not t.stop_gradient for t in tensor_inputs)
        with no_grad():
            outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)
        if needs_grad:
            def vjp_fn(cots):
                cots_t = (cots,) if not isinstance(cots, (tuple, list)) else cots
                with no_grad():
                    gin = cls.backward(ctx, *[Tensor(c) for c in cots_t])
                gin_t = (gin,) if not isinstance(gin, (tuple, list)) else tuple(gin)
                arrays = []
                gi = iter(gin_t)
                for a in args:
                    if isinstance(a, Tensor):
                        g = next(gi, None)
                        arrays.append(g._data if isinstance(g, Tensor) else
                                      (jnp.zeros_like(a._data) if g is None else jnp.asarray(g)))
                return tuple(arrays)
            node = GradNode(cls.__name__, vjp_fn, tuple(tensor_inputs),
                            tuple(o._data for o in outs_t))
            wrapped = []
            for i, o in enumerate(outs_t):
                t = Tensor(o._data, stop_gradient=False)
                t._grad_node = node
                t._out_slot = i
                wrapped.append(t)
            node.set_outputs(wrapped)
            return wrapped[0] if single else tuple(wrapped)
        return outs


class saved_tensors_hooks:
    """API-compatible stub: JAX residuals are immutable device arrays; pack/unpack
    hooks (used in the reference for CPU offload) map to jax remat policies instead."""

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook, self.unpack_hook = pack_hook, unpack_hook

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
