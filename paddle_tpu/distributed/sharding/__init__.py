"""paddle.distributed.sharding (reference: python/paddle/distributed/sharding)."""
from ...parallel.sharding import group_sharded_parallel, save_group_sharded_model  # noqa: F401
