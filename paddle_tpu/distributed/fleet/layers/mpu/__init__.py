"""fleet.layers.mpu compat (reference: fleet/layers/mpu/)."""
from ....parallel.mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,  # noqa: F401
                                    RowParallelLinear, ParallelCrossEntropy,
                                    RNGStatesTracker, get_rng_state_tracker,
                                    model_parallel_random_seed)
