"""Throughput / interval timers (reference: fleet/utils/timer_helper.py —
_Timer/_TimerGroup powering the hybrid-parallel trainers' ips logging).

TPU note: timings bracket host-side dispatch; for device-accurate intervals
call stop(sync=True), which materializes a scalar to drain the dispatch queue
(block_until_ready alone does not wait through the axon tunnel).
"""
from __future__ import annotations

import time

__all__ = ["get_timers", "set_timers", "Timer", "TimerGroup"]


class Timer:
    def __init__(self, name):
        self.name = name
        self._elapsed = 0.0
        self._count = 0
        self._started = False
        self._start_t = 0.0

    def start(self):
        if self._started:
            raise RuntimeError(f"timer {self.name!r} already started")
        self._started = True
        self._start_t = time.perf_counter()

    def stop(self, sync=False):
        if not self._started:
            raise RuntimeError(f"timer {self.name!r} not started")
        if sync:
            import jax
            import numpy as np
            # drain the device queue so the interval covers execution
            np.asarray(jax.device_put(0.0) + 0)
        self._elapsed += time.perf_counter() - self._start_t
        self._count += 1
        self._started = False

    def elapsed(self, reset=True):
        if self._started:   # fold the in-flight interval, keep running
            now = time.perf_counter()
            self._elapsed += now - self._start_t
            self._start_t = now               # reference _Timer restarts
        out = self._elapsed
        if reset:
            self._elapsed = 0.0
            self._count = 0
        return out

    def mean(self, reset=True):
        out = self._elapsed / max(self._count, 1)
        if reset:
            self._elapsed = 0.0
            self._count = 0
        return out


class TimerGroup:
    def __init__(self):
        self._timers = {}

    def __call__(self, name):
        if name not in self._timers:
            self._timers[name] = Timer(name)
        return self._timers[name]

    def log(self, names=None, normalizer=1.0, reset=True):
        names = names if names is not None else list(self._timers)
        parts = []
        for n in names:
            if n in self._timers:
                ms = 1000.0 * self._timers[n].elapsed(reset) / normalizer
                parts.append(f"{n}: {ms:.2f}ms")
        msg = "time (ms) | " + " | ".join(parts)
        print(msg)  # graftlint: disable=no-adhoc-telemetry (log() prints by contract)
        return msg

    def throughput(self, name, items, reset=True):
        """items/sec over the named timer's accumulated time (the reference's
        ips metric)."""
        t = self._timers[name].elapsed(reset)
        return items / t if t > 0 else float("inf")


_timers = None


def get_timers():
    global _timers
    if _timers is None:
        _timers = TimerGroup()
    return _timers


def set_timers(timers):
    global _timers
    _timers = timers
