"""fleet.utils compat (reference: fleet/utils/__init__.py)."""
from ..recompute import recompute, recompute_sequential, recompute_hybrid  # noqa: F401
from ....parallel import sequence_parallel as sequence_parallel_utils  # noqa: F401
from . import timer_helper  # noqa: F401
from .timer_helper import get_timers, set_timers  # noqa: F401
