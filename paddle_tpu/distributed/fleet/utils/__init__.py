"""fleet.utils compat (reference: fleet/utils/__init__.py)."""
from ..recompute import recompute, recompute_sequential, recompute_hybrid  # noqa: F401
from ....parallel import sequence_parallel as sequence_parallel_utils  # noqa: F401
