"""Hybrid ND topology (reference: python/paddle/distributed/fleet/base/
topology.py — CommunicateTopology:70, HybridCommunicateGroup:189).

TPU-native: the topology IS a jax.sharding.Mesh with named axes
[dp, pp, sharding, sep, mp, ep] (reference axis order topology.py:199, plus a
dedicated expert-parallel axis so TP and EP compose — the reference handles
this via moe sub-meshes, auto_parallel/static/pir_pass.py:368). Axis groups
become submeshes; collectives ride ICI via GSPMD/shard_map instead of NCCL rings.
"""
from __future__ import annotations

import itertools

import numpy as np
import jax

from ..auto_parallel.api import ProcessMesh

_HYBRID_AXES = ["dp", "pp", "sharding", "sep", "mp", "ep"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or _HYBRID_AXES)
        self._dims = list(dims or [1] * len(self._parallel_names))
        # older call sites pass 5 dims (pre-ep); pad trailing axes with 1
        self._dims += [1] * (len(self._parallel_names) - len(self._dims))
        self.coordinate = list(itertools.product(*(range(d) for d in self._dims)))
        self._world = int(np.prod(self._dims))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return self.coordinate.index(coord)

    def get_coord(self, rank):
        return dict(zip(self._parallel_names, self.coordinate[rank]))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in enumerate(self.coordinate) if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All groups along axis_name (each = ranks varying only in that axis)."""
        axis = self._parallel_names.index(axis_name)
        groups = {}
        for r, c in enumerate(self.coordinate):
            key = c[:axis] + c[axis + 1:]
            groups.setdefault(key, []).append(r)
        return list(groups.values())


class HybridCommunicateGroup:
    """reference: topology.py:189 — exposes per-axis rank/world-size/group plus
    the underlying ProcessMesh for GSPMD use."""

    def __init__(self, topology: CommunicateTopology, rank=None):
        from ..env import get_rank
        self._topo = topology
        self.global_rank = rank if rank is not None else get_rank()
        self.nranks = topology.world_size()
        names = topology.get_hybrid_group_names()
        dims = [topology.get_dim(n) for n in names]
        ids = np.arange(self.nranks).reshape(dims)
        self._mesh = ProcessMesh(ids, names)
        self._coord = topology.get_coord(self.global_rank) if self.nranks > 1 else \
            {n: 0 for n in names}

    # -- mesh access (TPU-native path) --
    def get_mesh(self) -> ProcessMesh:
        return self._mesh

    def topology(self):
        return self._topo

    # -- per-axis accessors (reference API) --
    def _axis(self, name):
        return self._coord.get(name, 0)

    def get_data_parallel_rank(self):
        return self._axis("dp")

    def get_data_parallel_world_size(self):
        return self._topo.get_dim("dp")

    def get_model_parallel_rank(self):
        return self._axis("mp")

    def get_model_parallel_world_size(self):
        return self._topo.get_dim("mp")

    def get_stage_id(self):
        return self._axis("pp")

    def get_pipe_parallel_rank(self):
        return self._axis("pp")

    def get_pipe_parallel_world_size(self):
        return self._topo.get_dim("pp")

    def get_sharding_parallel_rank(self):
        return self._axis("sharding")

    def get_sharding_parallel_world_size(self):
        return self._topo.get_dim("sharding")

    def get_sep_parallel_rank(self):
        return self._axis("sep")

    def get_sep_parallel_world_size(self):
        return self._topo.get_dim("sep")

    def get_expert_parallel_rank(self):
        return self._axis("ep")

    def get_expert_parallel_world_size(self):
        try:
            return self._topo.get_dim("ep")
        except ValueError:
            return 1

    # group objects (rank lists; collectives ride the mesh)
    def _group(self, name):
        from ..collective import new_group
        idx_axes = {n: self._axis(n) for n in self._topo.get_hybrid_group_names()
                    if n != name}
        ranks = [r for r in range(self.nranks)
                 if all(self._topo.get_coord(r)[k] == v for k, v in idx_axes.items())]
        return new_group(ranks)

    def get_data_parallel_group(self):
        return self._group("dp")

    def get_model_parallel_group(self):
        return self._group("mp")

    def get_pipe_parallel_group(self):
        return self._group("pp")

    def get_sharding_parallel_group(self):
        return self._group("sharding")

    def get_sep_parallel_group(self):
        return self._group("sep")

    def get_expert_parallel_group(self):
        return self._group("ep")

    def get_check_parallel_group(self, *a):
        return self._group("mp")

    def get_rank_from_stage(self, stage_id, **kwargs):
        coord = dict(self._coord)
        coord["pp"] = stage_id
        coord.update(kwargs)
        return self._topo.get_rank(**coord)


_hcg: HybridCommunicateGroup | None = None


def set_hybrid_communicate_group(hcg):
    global _hcg
    _hcg = hcg


def get_hybrid_communicate_group() -> HybridCommunicateGroup:
    global _hcg
    if _hcg is None:
        topo = CommunicateTopology(dims=[1, 1, 1, 1, 1])
        _hcg = HybridCommunicateGroup(topo)
    return _hcg
