"""fleet facade (reference: python/paddle/distributed/fleet/fleet.py:218 Fleet.init,
distributed_model fleet/model.py:32, distributed_optimizer fleet.py:1427).

TPU-native: fleet.init builds the hybrid ProcessMesh; distributed_model/optimizer
return mesh-aware wrappers whose math lowers to GSPMD collectives under jit.
"""
from __future__ import annotations

import numpy as np

from .topology import (CommunicateTopology, HybridCommunicateGroup,
                       set_hybrid_communicate_group, get_hybrid_communicate_group)
from ..env import get_rank, get_world_size, init_parallel_env
from . import topology  # noqa: F401


class DistributedStrategy:
    """reference: fleet/base/distributed_strategy.py (protobuf-backed there;
    plain attrs here).

    Wired flags: hybrid_configs, pipeline_configs, sharding, gradient_merge.
    The reference's `amp`/`recompute`/`tensor_parallel`/
    `find_unused_parameters` meta-optimizer switches map to first-class
    native mechanisms here instead; setting them True raises with a pointer
    (VERDICT r3: a stored-but-never-read flag is a silent no-op)."""

    _UNWIRED = {
        "amp": "use paddle_tpu.amp.auto_cast(level='O1'/'O2') + GradScaler "
               "around the train step",
        "recompute": "use paddle_tpu.distributed.fleet.recompute.recompute "
                     "(or the model's use_recompute config)",
        "tensor_parallel": "set hybrid_configs['mp_degree'] > 1 — GSPMD "
                           "lowers the mp collectives under jit",
        "find_unused_parameters": "not needed: GSPMD data parallelism "
                                  "reduces all grads; unused params simply "
                                  "get zero grads",
    }

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.amp_configs = {}
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {}
        self.tensor_parallel_configs = {}

    def __setattr__(self, name, value):
        if name in self._UNWIRED and value:
            raise NotImplementedError(
                f"DistributedStrategy.{name} is not a meta-optimizer pass in "
                f"paddle_tpu; {self._UNWIRED[name]}")
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        if name in DistributedStrategy._UNWIRED:
            return False
        raise AttributeError(name)

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._is_initialized = False
        self._role_maker = None
        self._ps_client = None
        self._ps_endpoint = None
        self._ps_load_dir = None

    def init(self, role_maker=None, is_collective=True, strategy=None, log_level="INFO"):
        """reference: fleet.py:218. With a non-collective role maker the
        runtime branches on the role (reference fleet.py:220-226): a SERVER
        process records its ps_sparse serving endpoint (started by
        run_server()), a TRAINER builds the PS client and init returns only
        once every server in PADDLE_PSERVERS_IP_PORT_LIST is reachable."""
        self._strategy = strategy or DistributedStrategy()
        if role_maker is None and not is_collective:
            # reference idiom: fleet.init(is_collective=False) builds the
            # PaddleCloud role maker internally (fleet.py:244)
            role_maker = PaddleCloudRoleMaker(is_collective=False)
        self._role_maker = role_maker
        if role_maker is not None and not getattr(
                role_maker, "_is_collective", True):
            return self._init_ps(role_maker)
        init_parallel_env()
        hc = self._strategy.hybrid_configs
        dims = [hc.get("dp_degree", 1), hc.get("pp_degree", 1),
                hc.get("sharding_degree", 1), hc.get("sep_degree", 1),
                hc.get("mp_degree", 1), hc.get("ep_degree", 1)]
        total = int(np.prod(dims))
        import jax
        n_dev = max(jax.device_count(), get_world_size())
        if total == 1 and n_dev > 1:
            dims[0] = n_dev  # default: pure DP over all devices
            total = n_dev
        topo = CommunicateTopology(
            ["dp", "pp", "sharding", "sep", "mp", "ep"], dims)
        self._hcg = HybridCommunicateGroup(topo)
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        return self

    # ---- parameter-server plane (reference fleet.py:220-226, 1268-1347) ----
    def _init_ps(self, rm):
        endpoints = rm.get_pserver_endpoints()
        if not endpoints:
            raise ValueError(
                "PS-mode fleet.init needs server endpoints "
                "(PADDLE_PSERVERS_IP_PORT_LIST or UserDefinedRoleMaker"
                "(server_endpoints=...))")
        self._is_initialized = True
        if rm.is_server():
            self._ps_endpoint = endpoints[rm.server_index()]
        else:
            from ..ps_sparse import SparsePsClient
            client = SparsePsClient(endpoints)
            for si in range(len(endpoints)):   # block until servers are up
                client._call(si, {"op": "stats"})
            self._ps_client = client
        return self

    def is_server(self):
        return (self._role_maker is not None
                and self._role_maker.is_server())

    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def ps_client(self):
        """The trainer-side PS client built by init (PS mode only)."""
        if self._ps_client is None:
            raise RuntimeError("fleet.init did not build a PS client "
                               "(not PS mode, or this is a server role)")
        return self._ps_client

    def init_server(self, dirname=None, **kwargs):
        """Record the checkpoint dir tables should warm-start from
        (reference: fleet.init_server)."""
        self._ps_load_dir = dirname

    def run_server(self):
        """Serve this process's shard (BLOCKING until a client sends
        shutdown) — reference: fleet.run_server."""
        import os
        if self._ps_endpoint is None:
            raise RuntimeError("run_server() requires fleet.init with a "
                               "SERVER-role role maker")
        from ..ps_sparse import serve
        host, port = self._ps_endpoint.rsplit(":", 1)
        idx = self._role_maker.server_index()
        data_dir = os.environ.get(
            "PADDLE_PS_DATA_DIR", os.path.join(".", "ps_data"))
        load_dir = (os.path.join(self._ps_load_dir, f"server_{idx}")
                    if self._ps_load_dir else None)
        serve(int(port), os.path.join(data_dir, f"server_{idx}"), host=host,
              load_dir=load_dir)

    def stop_worker(self):
        """Trainer teardown: drop PS connections (reference:
        fleet.stop_worker)."""
        if self._ps_client is not None:
            self._ps_client.close()

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def is_first_worker(self):
        return get_rank() == 0

    def barrier_worker(self):
        from ..collective import barrier
        barrier()

    def distributed_model(self, model):
        """reference: fleet/model.py:32 — picks the wrapper by topology."""
        hc = self._hcg
        if hc.get_pipe_parallel_world_size() > 1:
            from ...parallel.pipeline_layer import PipelineParallel
            return PipelineParallel(model, hc, self._strategy)
        if hc.get_model_parallel_world_size() > 1 or hc.get_sep_parallel_world_size() > 1:
            from ...parallel.tensor_parallel import TensorParallel
            return TensorParallel(model, hc, self._strategy)
        if hc.get_data_parallel_world_size() > 1:
            from ..parallel import DataParallel
            return DataParallel(model)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        from .hybrid_optimizer import HybridParallelOptimizer
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       strategy or self._strategy)


fleet = Fleet()
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = get_hybrid_communicate_group
worker_index = lambda: get_rank()  # noqa: E731
worker_num = lambda: get_world_size()  # noqa: E731


class Role:
    """reference fleet/base/role_maker.py Role enum."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """reference: fleet/base/role_maker.py:548 — roles derived from the
    PaddleCloud env contract: TRAINING_ROLE (TRAINER|PSERVER),
    PADDLE_PSERVERS_IP_PORT_LIST, PADDLE_TRAINER_ENDPOINTS, POD_IP +
    PADDLE_PORT. In PS mode the server endpoints feed
    distributed.ps_sparse servers; collective mode falls back to the
    launch env (rank/world)."""

    def __init__(self, is_collective=True, **kwargs):
        import os
        self._is_collective = is_collective
        self._role = Role.WORKER
        self._servers = [e for e in os.environ.get(
            "PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e]
        self._workers = [e for e in os.environ.get(
            "PADDLE_TRAINER_ENDPOINTS", "").split(",") if e]
        if not is_collective and os.environ.get(
                "TRAINING_ROLE", "TRAINER").upper() == "PSERVER":
            self._role = Role.SERVER
            me = (os.environ.get("POD_IP", "127.0.0.1") + ":"
                  + os.environ.get("PADDLE_PORT", "0"))
            if me not in self._servers:
                raise ValueError(
                    f"TRAINING_ROLE=PSERVER but {me!r} is not in "
                    f"PADDLE_PSERVERS_IP_PORT_LIST={self._servers}; check "
                    "POD_IP/PADDLE_PORT (two servers claiming the same "
                    "shard would silently corrupt training)")
            self._server_index = self._servers.index(me)
        else:
            self._server_index = -1

    # -- worker plane ---------------------------------------------------------
    def worker_index(self):
        return get_rank()

    def worker_num(self):
        if self._is_collective:
            return get_world_size()      # launch env is authoritative
        return len(self._workers) or get_world_size()

    def is_worker(self):
        return self._role == Role.WORKER

    def is_first_worker(self):
        return self.is_worker() and self.worker_index() == 0

    def get_trainer_endpoints(self):
        return list(self._workers)

    # -- server plane ---------------------------------------------------------
    def is_server(self):
        return self._role == Role.SERVER

    def server_num(self):
        return len(self._servers)

    def server_index(self):
        return self._server_index

    def get_pserver_endpoints(self):
        return list(self._servers)

    def role_id(self):
        return self.server_index() if self.is_server() else             self.worker_index()


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference: fleet/base/role_maker.py:1213 — explicit roles instead of
    env derivation."""

    def __init__(self, is_collective=False, current_id=0, role=None,
                 worker_num=0, server_endpoints=None, **kwargs):
        self._is_collective = is_collective
        self._role = role if role is not None else Role.WORKER
        self._servers = list(server_endpoints or [])
        self._workers = []
        self._current_id = int(current_id)
        self._worker_num = int(worker_num)
        self._server_index = self._current_id             if self._role == Role.SERVER else -1

    def worker_index(self):
        return self._current_id if self._role == Role.WORKER else -1

    def worker_num(self):
        return self._worker_num
