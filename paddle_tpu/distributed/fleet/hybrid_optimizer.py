"""HybridParallelOptimizer + GradScaler hook (reference:
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:266,
fleet/scaler.py:28).

On TPU the mp/pp-aware grad-clip subtleties (partial norms per shard) are
handled by computing the global norm over the full (sharded) arrays — GSPMD
reduces across shards inside jit, so the reference's per-group norm allreduce
disappears.
"""
from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        # DistributedStrategy.gradient_merge (reference
        # meta_optimizers/gradient_merge_optimizer.py): accumulate k_steps
        # micro-batches of grads, apply the update on the k-th step, divide
        # by k when avg=True. clear_grad mid-merge is suppressed so the
        # canonical `step(); clear_grad()` loop keeps accumulating.
        gm = bool(strategy is not None and
                  getattr(strategy, "gradient_merge", False))
        cfg = getattr(strategy, "gradient_merge_configs", {}) if gm else {}
        self._gm_steps = max(1, int(cfg.get("k_steps", 1))) if gm else 1
        self._gm_avg = bool(cfg.get("avg", True))
        self._gm_counter = 0

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)

    def step(self):
        if self._gm_steps > 1:
            self._gm_counter += 1
            if self._gm_counter < self._gm_steps:
                return                  # merge window open: accumulate only
            self._gm_counter = 0
            if self._gm_avg:
                from ...core.selected_rows import SelectedRows
                k = float(self._gm_steps)
                for p in getattr(self._inner_opt, "_parameter_list", []):
                    if isinstance(p.grad, SelectedRows):
                        # row-sparse grad (Embedding(sparse=True)): scale the
                        # values in place, keeping the rows/height structure
                        sr = p.grad
                        p.grad = SelectedRows(sr.rows, sr.values / k,
                                              sr.height)
                    elif p.grad is not None:
                        p.grad.set_value(p.grad / k)
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        if self._gm_steps > 1 and self._gm_counter != 0:
            return                      # mid-merge: keep accumulated grads
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


class HybridParallelGradScaler:
    """reference: fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_gradscaler.py."""

    def __init__(self, scaler, hcg=None):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self.__dict__["_scaler"], name)

    def scale(self, var):
        return self._scaler.scale(var)

    def step(self, optimizer):
        inner = getattr(optimizer, "_inner_opt", optimizer)
        return self._scaler.step(inner)

    def minimize(self, optimizer, scaled_loss):
        inner = getattr(optimizer, "_inner_opt", optimizer)
        return self._scaler.minimize(inner, scaled_loss)


def distributed_scaler(scaler):
    """reference: fleet/scaler.py:28."""
    from .topology import get_hybrid_communicate_group
    return HybridParallelGradScaler(scaler, get_hybrid_communicate_group())
