"""HybridParallelOptimizer + GradScaler hook (reference:
fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:266,
fleet/scaler.py:28).

On TPU the mp/pp-aware grad-clip subtleties (partial norms per shard) are
handled by computing the global norm over the full (sharded) arrays — GSPMD
reduces across shards inside jit, so the reference's per-group norm allreduce
disappears.
"""
from __future__ import annotations


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self.__dict__["_inner_opt"], name)

    def step(self):
        self._inner_opt.step()

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)


class HybridParallelGradScaler:
    """reference: fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_gradscaler.py."""

    def __init__(self, scaler, hcg=None):
        self._scaler = scaler
        self._hcg = hcg

    def __getattr__(self, name):
        return getattr(self.__dict__["_scaler"], name)

    def scale(self, var):
        return self._scaler.scale(var)

    def step(self, optimizer):
        inner = getattr(optimizer, "_inner_opt", optimizer)
        return self._scaler.step(inner)

    def minimize(self, optimizer, scaled_loss):
        inner = getattr(optimizer, "_inner_opt", optimizer)
        return self._scaler.minimize(inner, scaled_loss)


def distributed_scaler(scaler):
    """reference: fleet/scaler.py:28."""
    from .topology import get_hybrid_communicate_group
    return HybridParallelGradScaler(scaler, get_hybrid_communicate_group())
