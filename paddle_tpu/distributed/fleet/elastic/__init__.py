"""Elastic training manager (reference: python/paddle/distributed/fleet/
elastic/manager.py:125 ElasticManager — ETCD heartbeats, scale in/out
detection, restart decisions; launch integrates via --max_restarts).

TPU framing: the heartbeat plane is TCPStore (native C++ daemon when
available) instead of ETCD; the manager watches per-rank heartbeats,
reports the alive world, and decides restart vs wait. The launch CLI's
restart loop (launch/main.py --max_restarts) is the actuator."""
from __future__ import annotations

import threading
import time

from ...store import TCPStore

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"        # waiting for ranks (scale event in progress)
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Heartbeat + membership over the store.

    Each rank calls start() (spawns a heartbeat thread) and the supervisor
    polls watch(): READY when np_min <= alive <= np_max and stable, HOLD
    while members are joining, RESTART when a previously-alive rank went
    silent past `timeout` (the reference restarts the job group on ETCD
    watch events)."""

    def __init__(self, rank, store=None, host="127.0.0.1", port=0,
                 np_min=1, np_max=None, heartbeat_interval=1.0,
                 timeout=10.0, job_id="default"):
        self.rank = rank
        self.np_min = np_min
        self.np_max = np_max
        self.interval = heartbeat_interval
        self.timeout = timeout
        self.prefix = f"elastic/{job_id}"
        self.store = store if store is not None else TCPStore(
            host=host, port=port, is_master=(rank == 0))
        self._stop = threading.Event()
        self._thread = None

    # -- heartbeat plane ------------------------------------------------------
    def _beat_key(self, rank):
        return f"{self.prefix}/beat/{rank}"

    def _beat(self):
        while not self._stop.is_set():
            self.store.set(self._beat_key(self.rank), time.time())
            self._stop.wait(self.interval)

    def start(self):
        self.store.set(self._beat_key(self.rank), time.time())
        self.store.set(f"{self.prefix}/seen/{self.rank}", 1)
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- membership -----------------------------------------------------------
    def _probe(self, world):
        """(ranks expected alive, ranks with a fresh heartbeat). A rank that
        called mark_finished() completed cleanly — it is excluded from both,
        so a finished member never reads as a fault."""
        now = time.time()
        seen, alive = [], []
        for r in range(world):
            try:
                self.store.get(f"{self.prefix}/seen/{r}", timeout=0.05)
            except Exception:
                continue
            try:
                self.store.get(f"{self.prefix}/finished/{r}", timeout=0.05)
                continue   # clean exit, not a member anymore
            except Exception:
                pass
            seen.append(r)
            try:
                t = self.store.get(self._beat_key(r), timeout=0.05)
                if now - float(t) <= self.timeout:
                    alive.append(r)
            except Exception:
                pass
        return seen, alive

    def alive_ranks(self, world_hint=None):
        world = world_hint or (self.np_max or self.np_min)
        return self._probe(world)[1]

    def watch(self, world_hint=None):
        """One membership observation -> ElasticStatus."""
        world = world_hint or (self.np_max or self.np_min)
        seen, alive = self._probe(world)
        if seen and not alive:
            return ElasticStatus.ERROR
        if len(seen) > len(alive):
            # someone was here and went silent -> group must restart
            # (a rejoining rank refreshes its beat and clears this);
            # takes priority over HOLD: a dead member is a fault, not a
            # not-yet-joined member
            return ElasticStatus.RESTART
        if len(alive) < self.np_min:
            return ElasticStatus.HOLD
        if self.np_max is not None and len(alive) > self.np_max:
            return ElasticStatus.HOLD
        return ElasticStatus.COMPLETED

    def mark_finished(self):
        self.store.set(f"{self.prefix}/finished/{self.rank}", 1)
