"""Elastic training manager (reference: python/paddle/distributed/fleet/
elastic/manager.py:125 ElasticManager — ETCD heartbeats, scale in/out
detection, restart decisions; launch integrates via --max_restarts).

TPU framing: the heartbeat plane is TCPStore (native C++ daemon when
available) instead of ETCD; the manager watches per-rank heartbeats,
reports the alive world, and decides restart vs wait. The launch CLI's
restart loop (launch/main.py --max_restarts) is the actuator."""
from __future__ import annotations

import threading
import time

from ...store import TCPStore

__all__ = ["ElasticManager", "ElasticStatus"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"        # waiting for ranks (scale event in progress)
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Heartbeat + membership over the store.

    Each rank calls start() (spawns a heartbeat thread) and the supervisor
    polls watch(): READY when np_min <= alive <= np_max and stable, HOLD
    while members are joining, RESTART when a previously-alive rank went
    silent past `timeout` (the reference restarts the job group on ETCD
    watch events)."""

    def __init__(self, rank, store=None, host="127.0.0.1", port=0,
                 np_min=1, np_max=None, heartbeat_interval=1.0,
                 timeout=10.0, job_id="default"):
        self.rank = rank
        self.np_min = np_min
        self.np_max = np_max
        self.interval = heartbeat_interval
        self.timeout = timeout
        self.prefix = f"elastic/{job_id}"
        self.store = store if store is not None else TCPStore(
            host=host, port=port, is_master=(rank == 0))
        self._stop = threading.Event()
        self._thread = None

    # -- heartbeat plane ------------------------------------------------------
    def _beat_key(self, rank):
        return f"{self.prefix}/beat/{rank}"

    def _beat(self):
        while not self._stop.is_set():
            # wall clock on purpose: beat values are compared across
            # processes, where monotonic clocks are not comparable
            self.store.set(self._beat_key(self.rank), time.time())  # graftlint: disable=no-adhoc-telemetry
            self._stop.wait(self.interval)

    def start(self):
        self.store.set(self._beat_key(self.rank), time.time())  # graftlint: disable=no-adhoc-telemetry
        self.store.set(f"{self.prefix}/seen/{self.rank}", 1)
        self._thread = threading.Thread(target=self._beat, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- membership -----------------------------------------------------------
    def _probe(self, world):
        """(ranks expected alive, ranks with a fresh heartbeat). A rank that
        called mark_finished() completed cleanly — it is excluded from both,
        so a finished member never reads as a fault."""
        now = time.time()  # graftlint: disable=no-adhoc-telemetry (cross-process compare)
        seen, alive = [], []
        for r in range(world):
            try:
                self.store.get(f"{self.prefix}/seen/{r}", timeout=0.05)
            except Exception:
                continue
            try:
                self.store.get(f"{self.prefix}/finished/{r}", timeout=0.05)
                continue   # clean exit, not a member anymore
            except Exception:
                pass
            seen.append(r)
            try:
                t = self.store.get(self._beat_key(r), timeout=0.05)
                if now - float(t) <= self.timeout:
                    alive.append(r)
            except Exception:
                pass
        return seen, alive

    def alive_ranks(self, world_hint=None):
        world = world_hint or (self.np_max or self.np_min)
        return self._probe(world)[1]

    def watch(self, world_hint=None):
        """One membership observation -> ElasticStatus."""
        world = world_hint or (self.np_max or self.np_min)
        seen, alive = self._probe(world)
        if seen and not alive:
            return ElasticStatus.ERROR
        if len(seen) > len(alive):
            # someone was here and went silent -> group must restart
            # (a rejoining rank refreshes its beat and clears this);
            # takes priority over HOLD: a dead member is a fault, not a
            # not-yet-joined member
            return ElasticStatus.RESTART
        if len(alive) < self.np_min:
            return ElasticStatus.HOLD
        if self.np_max is not None and len(alive) > self.np_max:
            return ElasticStatus.HOLD
        return ElasticStatus.COMPLETED

    def mark_finished(self):
        self.store.set(f"{self.prefix}/finished/{self.rank}", 1)


class PreemptionCheckpointer:
    """Preemption-aware checkpoint-restart (SURVEY §7 "preemption-aware
    checkpoint-restart (TPU maintenance events)"; reference capability:
    fleet/elastic/manager.py fault-tolerance levels).

    A TPU maintenance event / preemption delivers SIGTERM (to every worker on
    the machine) with notice. The signal handler only sets a flag; at the
    next step boundary the rank writes its checkpoint shard through
    paddle_tpu.distributed.checkpoint and exits with EXIT_CODE — nonzero, so
    `launch --max_restarts` respawns the group — and resume() continues from
    the newest checkpoint COMPLETE across all ranks. Data-parallel training
    synchronizes ranks every step (grad allreduce), so all ranks reach the
    same boundary and the per-rank shards form a consistent step.

    Layout: root/step_{k}/rank_{r}/ (per-rank orbax tree) + rank_{r}.done
    markers; a step is complete when all world ranks' markers exist.
    """

    EXIT_CODE = 75        # EX_TEMPFAIL: restartable failure

    def __init__(self, root, get_state, set_state, rank=None, world=None,
                 signals=None):
        import os
        import signal as _signal
        from ... import get_rank, get_world_size
        self.root = os.path.abspath(root)
        self.get_state = get_state
        self.set_state = set_state
        self.rank = get_rank() if rank is None else rank
        self.world = get_world_size() if world is None else world
        self.signals = signals if signals is not None else [_signal.SIGTERM]
        self._flag = False

    # -- signal plane ---------------------------------------------------------
    def install(self):
        import signal as _signal
        for s in self.signals:
            _signal.signal(s, self._on_signal)
        return self

    def _on_signal(self, signum, frame):
        self._flag = True

    @property
    def preempted(self):
        return self._flag

    # -- step-boundary protocol -----------------------------------------------
    def maybe_checkpoint(self, step):
        """Call at the TOP of each training step with the step about to run.
        Returns normally when training should continue; checkpoints and
        exits the process when a preemption was delivered."""
        import os
        import sys
        if not self._flag:
            return
        self._save(step)
        sys.stdout.flush()
        sys.stderr.flush()
        # os._exit, NOT sys.exit: the jax.distributed atexit shutdown is a
        # cross-process barrier, and peers exit at their own boundaries — a
        # preempting rank must not wait on it
        os._exit(self.EXIT_CODE)

    def _save(self, step):
        """Per-rank host-state shard as npz + json meta. Deliberately NOT the
        orbax path: orbax coordinates multihost commits globally, but each
        rank here saves independently while peers may already be gone."""
        import os
        import json
        import numpy as np
        d = os.path.join(self.root, f"step_{step}")
        os.makedirs(d, exist_ok=True)
        state = self.get_state()
        arrays = {k: np.asarray(v._data if hasattr(v, "_data") else v)
                  for k, v in state.items()}
        tmp = os.path.join(d, f"rank_{self.rank}.npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, os.path.join(d, f"rank_{self.rank}.npz"))
        # the marker's EXISTENCE is the commit point _scan trusts, so it
        # must appear atomically — a torn marker would count a half-saved
        # rank as done
        marker_tmp = os.path.join(d, f"rank_{self.rank}.done.tmp")
        with open(marker_tmp, "w") as f:
            # world in the marker: a restart at a different scale must judge
            # completeness against the world that WROTE the step, not its own
            json.dump({"rank": self.rank, "step": step,
                       "world": self.world}, f)
        os.replace(marker_tmp, os.path.join(d, f"rank_{self.rank}.done"))

    # -- restart plane --------------------------------------------------------
    def _scan(self):
        """Newest step whose WRITER world is fully done -> (step, world).
        The writer world comes from the done markers themselves (markers
        written before r4 carry no world field and are judged against the
        current world)."""
        import glob
        import json
        import os
        best = None
        for d in glob.glob(os.path.join(self.root, "step_*")):
            try:
                k = int(os.path.basename(d).split("_")[1])
            except ValueError:
                continue
            markers = glob.glob(os.path.join(d, "rank_*.done"))
            if not markers:
                continue
            try:
                with open(sorted(markers)[0]) as f:
                    writer_world = int(json.load(f).get("world", self.world))
            except (OSError, ValueError):
                writer_world = self.world
            done = [os.path.exists(os.path.join(d, f"rank_{r}.done"))
                    for r in range(writer_world)]
            if all(done) and (best is None or k > best[0]):
                best = (k, writer_world)
        return best

    def latest_complete_step(self):
        found = self._scan()
        return None if found is None else found[0]

    def resume(self):
        """Load the newest complete checkpoint into the live state (in place
        on the get_state() tensors, then set_state for anything else).
        Returns the step to continue FROM, or None when no complete
        checkpoint exists (fresh start).

        World-size changes (reference elastic scale-in/out,
        fleet/elastic/manager.py:125,177): when the checkpoint was written by
        a DIFFERENT world, rank r restores rank r % writer_world's shard.
        For the state this checkpointer holds — data-parallel-replicated
        params/optimizer moments and host counters — every writer shard
        agrees, so the mapping IS the reshard. Genuinely sharded device
        state (ZeRO/mp) belongs in paddle_tpu.distributed.checkpoint (orbax),
        which reshards on load by sharding spec."""
        import os
        import logging
        import numpy as np
        import jax.numpy as jnp
        found = self._scan()
        if found is None:
            return None
        k, writer_world = found
        src_rank = self.rank % writer_world
        if writer_world != self.world:
            logging.getLogger("paddle_tpu.elastic").warning(
                "resuming step %d written by world=%d at world=%d: rank %d "
                "restores shard %d (replicated-state reshard)",
                k, writer_world, self.world, self.rank, src_rank)
        state = self.get_state()
        with np.load(os.path.join(self.root, f"step_{k}",
                                  f"rank_{src_rank}.npz")) as z:
            for key, dst in state.items():
                if key not in z:
                    raise KeyError(f"checkpoint missing key {key}")
                arr = jnp.asarray(z[key])
                if hasattr(dst, "_data"):
                    dst._data = arr.astype(dst._data.dtype)
                else:
                    # non-tensor state (step counters, numpy buffers):
                    # hand the restored value to set_state
                    state[key] = np.asarray(z[key])
        self.set_state(state)
        return k


__all__ += ["PreemptionCheckpointer"]
