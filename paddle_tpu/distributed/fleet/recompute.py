"""Activation recomputation (reference: python/paddle/distributed/fleet/
recompute/recompute.py — RecomputeFunction:128, recompute():459,
recompute_sequential:626; recompute_hybrid.py:265).

Tape-level recompute: forward runs under no_grad (no residuals saved); backward
re-executes the function with the tape enabled and pulls gradients through.
Works eagerly AND under program capture — in a captured program XLA sees the
recomputation, i.e. this is rematerialization (jax.checkpoint's effect) with
Paddle's API. RNG state is snapshotted and replayed so dropout masks match
(the reference's mp-aware RNGStatesTracker replay)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import unwrap, _state
from ...autograd import no_grad
from ...autograd.backward import backward as _tape_backward
from ...autograd.node import GradNode
from ...core import rng as rng_mod


def recompute(function, *args, **kwargs):
    preserve_rng_state = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)  # noqa: F841
    tensor_inputs = [a for a in args if isinstance(a, Tensor)]
    from ...core.dispatch import grad_enabled
    needs_grad = grad_enabled() and any(not t.stop_gradient for t in tensor_inputs)

    rng_snapshot = unwrap(rng_mod.default_generator().get_state()) \
        if preserve_rng_state else None

    with no_grad():
        outs = function(*args, **kwargs)
    if not needs_grad:
        return outs

    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(o for o in outs)
    out_arrays = tuple(unwrap(o) for o in outs_t if isinstance(o, Tensor))

    def vjp_fn(cots):
        cots_t = (cots,) if not isinstance(cots, (tuple, list)) else tuple(cots)
        # replay rng so dropout masks match the forward
        gen = rng_mod.default_generator()
        saved_state = gen.get_state()._data if preserve_rng_state else None
        if preserve_rng_state:
            gen._state._data = rng_snapshot
        # re-run forward WITH tape on detached inputs
        detached = []
        for a in args:
            if isinstance(a, Tensor):
                d = a.detach()
                d.stop_gradient = a.stop_gradient
                detached.append(d)
            else:
                detached.append(a)
        re_outs = function(*detached, **kwargs)
        if preserve_rng_state and saved_state is not None:
            gen._state._data = saved_state
        re_outs_t = (re_outs,) if not isinstance(re_outs, (tuple, list)) \
            else tuple(re_outs)
        grads_in = [Tensor(c) for c in cots_t]
        roots = [o for o in re_outs_t if isinstance(o, Tensor) and not o.stop_gradient]
        gts = [g for o, g in zip([o for o in re_outs_t if isinstance(o, Tensor)],
                                 grads_in) if not o.stop_gradient]
        # mark detached leaves to retain grads
        leaves = [d for d in detached if isinstance(d, Tensor) and not d.stop_gradient]
        for l in leaves:
            l._retain_grad = True
        _tape_backward(roots, gts)
        result = []
        for a, d in zip(args, detached):
            if isinstance(a, Tensor):
                if d.grad is not None:
                    result.append(d.grad._data)
                else:
                    result.append(jnp.zeros_like(unwrap(a)))
        return tuple(result)

    node = GradNode("recompute", vjp_fn, tuple(tensor_inputs), out_arrays)
    wrapped = []
    i = 0
    final = []
    for o in outs_t:
        if isinstance(o, Tensor):
            t = Tensor(unwrap(o), stop_gradient=False)
            t._grad_node = node
            t._out_slot = i
            i += 1
            wrapped.append(t)
            final.append(t)
        else:
            final.append(o)
    node.set_outputs(wrapped)
    return final[0] if single else tuple(final)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference :626 — recompute over a Sequential in segments."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    seg_size = max(len(layers) // max(segments, 1), 1)

    def run_segment(start, end):
        def seg_fn(x):
            for l in layers[start:end]:
                x = l(x)
            return x
        return seg_fn

    x = args[0]
    start = 0
    while start < len(layers):
        end = min(start + seg_size, len(layers))
        x = recompute(run_segment(start, end), x, **kwargs)
        start = end
    return x


def recompute_hybrid(ctx, function, *args, **kwargs):
    """reference recompute_hybrid.py:265 — mp-aware rng + offload. On TPU the
    rng story is the global key (identical by construction) and offload maps to
    XLA rematerialization, so this is plain recompute."""
    return recompute(function, *args, **kwargs)
