"""fleet.meta_parallel compat (reference: fleet/meta_parallel/__init__.py)."""
from ....parallel.pipeline_layer import (PipelineLayer, LayerDesc,  # noqa: F401
                                         SharedLayerDesc, PipelineParallel,
                                         PipelineParallelWithInterleave,
                                         ZeroBubblePipelineParallel)
from ....parallel.tensor_parallel import TensorParallel, SegmentParallel  # noqa: F401
from ....parallel.mp_layers import (VocabParallelEmbedding, ColumnParallelLinear,  # noqa: F401
                                    RowParallelLinear, ParallelCrossEntropy,
                                    get_rng_state_tracker)
