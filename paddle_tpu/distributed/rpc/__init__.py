"""paddle.distributed.rpc analog (reference: python/paddle/distributed/rpc/
rpc.py — init_rpc/rpc_sync/rpc_async/shutdown over a brpc C++ agent).

TPU-native framing: RPC is host-side control-plane (parameter-server
coordination, elastic orchestration, user-defined remote calls) — tensor
traffic stays on XLA collectives. The agent is a thread-per-connection
socket server; discovery and the shutdown barrier ride TCPStore (whose
daemon is the native C++ one when available)."""
from __future__ import annotations

import concurrent.futures
import pickle
import socket
import struct
import threading
import time

from ..store import TCPStore

__all__ = ["init_rpc", "rpc_sync", "rpc_async", "shutdown",
           "get_worker_info", "get_all_worker_infos",
           "get_current_worker_info", "WorkerInfo"]


class WorkerInfo:
    """reference: rpc.py WorkerInfo(name, rank, ip, port)."""

    def __init__(self, name, rank, ip, port):
        self.name, self.rank, self.ip, self.port = name, rank, ip, port

    def __repr__(self):
        return (f"WorkerInfo(name={self.name}, rank={self.rank}, "
                f"ip={self.ip}, port={self.port})")


_state = threading.local()
_srv = None
_store = None
_infos: dict[str, WorkerInfo] = {}
_self_info: WorkerInfo | None = None
_conns: dict[str, socket.socket] = {}
_conn_locks: dict[str, threading.Lock] = {}
_conn_lock = threading.Lock()     # guards the two dicts, never held over IO
_pool = None


def _send_blob(sock, data):
    sock.sendall(struct.pack("!I", len(data)) + data)


def _recv_blob(sock):
    hdr = b""
    while len(hdr) < 4:
        c = sock.recv(4 - len(hdr))
        if not c:
            raise ConnectionError("rpc connection closed")
        hdr += c
    (n,) = struct.unpack("!I", hdr)
    buf = b""
    while len(buf) < n:
        c = sock.recv(min(1 << 20, n - len(buf)))
        if not c:
            raise ConnectionError("rpc connection closed")
        buf += c
    return buf


class _Agent(threading.Thread):
    """Serves incoming calls: recv (fn, args, kwargs) -> send (ok, result)."""

    def __init__(self):
        super().__init__(daemon=True)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind(("0.0.0.0", 0))
            self._srv.listen(64)
            self.port = self._srv.getsockname()[1]
        except OSError:
            # a bind failure must not leak the listener fd: the caller
            # never gets an agent to close()
            self._srv.close()
            raise

    def run(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                fn, args, kwargs = pickle.loads(_recv_blob(conn))
                try:
                    out = (True, fn(*args, **kwargs))
                except Exception as e:       # deliver remote exceptions
                    out = (False, e)
                _send_blob(conn, pickle.dumps(out))
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            conn.close()

    def close(self):
        self._srv.close()


def _local_ip(master_host):
    """The interface IP that actually routes to the master (UDP-connect
    trick) — gethostbyname(hostname) is wrong in containers."""
    if master_host in ("127.0.0.1", "localhost", "0.0.0.0"):
        return "127.0.0.1"
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((master_host, 1))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def init_rpc(name, rank=None, world_size=None, master_endpoint=None):
    """reference: rpc.py:85. Starts the agent, registers name->endpoint in
    the store, blocks until all world_size workers registered."""
    global _srv, _store, _self_info
    from ..env import get_rank, get_world_size
    rank = get_rank() if rank is None else rank
    world_size = get_world_size() if world_size is None else world_size
    master_endpoint = master_endpoint or "127.0.0.1:0"
    host, port = master_endpoint.rsplit(":", 1)
    _srv = _Agent()
    _srv.start()
    _store = TCPStore(host=host, port=int(port), is_master=(rank == 0),
                      world_size=world_size)
    ip = _local_ip(host)
    _self_info = WorkerInfo(name, rank, ip, _srv.port)
    _store.set(f"rpc/worker/{rank}", (name, rank, ip, _srv.port))
    _store.wait([f"rpc/worker/{r}" for r in range(world_size)])
    for r in range(world_size):
        n, rk, wip, wport = _store.get(f"rpc/worker/{r}")
        _infos[n] = WorkerInfo(n, rk, wip, wport)
    return _store.port


def _connect(to):
    info = _infos.get(to)
    if info is None:
        raise ValueError(f"unknown rpc worker {to!r} "
                         f"(known: {sorted(_infos)})")
    with _conn_lock:
        lock = _conn_locks.setdefault(to, threading.Lock())
        sock = _conns.get(to)
    if sock is None:
        with lock:
            with _conn_lock:
                sock = _conns.get(to)
            if sock is None:
                sock = socket.create_connection((info.ip, info.port),
                                                timeout=30)
                try:
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                except OSError:
                    sock.close()
                    raise
                with _conn_lock:
                    _conns[to] = sock
    return sock, lock


def rpc_sync(to, fn, args=None, kwargs=None, timeout=-1):
    """reference: rpc.py:160 — blocking remote call, returns the result.
    Per-destination locking: calls to different peers run concurrently and
    one hung peer can't wedge calls to the others."""
    sock, lock = _connect(to)
    with lock:
        sock.settimeout(None if timeout in (-1, None) else timeout)
        _send_blob(sock, pickle.dumps((fn, tuple(args or ()),
                                       dict(kwargs or {}))))
        ok, out = pickle.loads(_recv_blob(sock))
    if not ok:
        raise out
    return out


def rpc_async(to, fn, args=None, kwargs=None, timeout=-1):
    """reference: rpc.py:206 — returns a future with .wait()."""
    global _pool
    if _pool is None:
        _pool = concurrent.futures.ThreadPoolExecutor(max_workers=8)
    fut = _pool.submit(rpc_sync, to, fn, args, kwargs, timeout)
    fut.wait = fut.result   # paddle futures use .wait()
    return fut


def shutdown():
    """reference: rpc.py shutdown — barrier then teardown."""
    global _srv, _store, _self_info, _pool
    if _store is None:
        return
    n = _store.add("rpc/shutdown", 1)
    world = len(_infos)
    deadline = time.monotonic() + 300
    while _store.add("rpc/shutdown", 0) < world:
        if time.monotonic() > deadline:
            raise TimeoutError("rpc shutdown barrier timed out")
        time.sleep(0.02)
    with _conn_lock:
        for s in _conns.values():
            s.close()
        _conns.clear()
        _conn_locks.clear()
    if _srv is not None:
        _srv.close()
    if _pool is not None:
        _pool.shutdown(wait=False)
    _srv = _store = _self_info = _pool = None
    _infos.clear()


def get_worker_info(name):
    return _infos[name]


def get_all_worker_infos():
    return list(_infos.values())


def get_current_worker_info():
    return _self_info
