"""Analytic parallelism cost model (reference:
python/paddle/distributed/auto_parallel/static/cost/estimate_cost.py +
base_cost.py + comm_op_cost.py — per-op compute/communication estimates that
let the search RANK candidates it never runs).

TPU framing: a candidate is a (dp, mp, pp, sharding, micro_batch) layout of
a transformer workload over a chip count.  The estimate decomposes a train
step into:

  compute    — model flops / (chips x peak x matmul-efficiency); efficiency
               degrades when mp slices contractions below the 128/256-wide
               MXU sweet spot (the scaling-book "shrinking matmul" effect).
  tp_comm    — Megatron TP: 2 all-reduces of activations per layer forward
               (+2 backward), ring cost 2(n-1)/n x bytes / ici_bw.
  grad_sync  — dp all-reduce (or sharding reduce-scatter+all-gather, same
               ring volume) of the local parameter bytes, once per step.
  pp         — GPipe/1F1B bubble (pp-1)/(m+pp-1) stretching compute+tp, plus
               per-microbatch boundary activation sends.
  memory     — params x (weight+grad+opt bytes, sharded as the layout
               shards them) + activation working set; a candidate whose
               per-chip bytes exceed HBM is infeasible (cost = inf), which
               is the analytic pruning the empirical tuner cannot do.

Numbers are deliberately coarse (public spec sheets, overridable): the model
exists to ORDER candidates and rule out infeasible ones so the empirical
tuner (auto_tuner.run_trials) spends its trial budget on the plausible few.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

__all__ = ["HardwareSpec", "ModelDesc", "AnalyticCostModel", "HW_PRESETS"]


@dataclass
class HardwareSpec:
    peak_flops: float = 197e12      # bf16 per chip
    hbm_bytes: float = 16e9
    hbm_bw: float = 819e9
    ici_bw: float = 90e9            # effective per-direction bytes/s
    ici_latency: float = 1e-6       # per collective hop


HW_PRESETS = {
    "v5e": HardwareSpec(197e12, 16e9, 819e9, 90e9),
    "v5p": HardwareSpec(459e12, 95e9, 2765e9, 300e9),
    "v4": HardwareSpec(275e12, 32e9, 1228e9, 135e9),
    "v6e": HardwareSpec(918e12, 32e9, 1640e9, 180e9),
}


@dataclass
class ModelDesc:
    num_layers: int
    hidden: int
    seq_len: int
    vocab: int = 32000
    intermediate: int = None        # default 4x hidden
    global_batch: int = 8
    dtype_bytes: int = 2            # bf16 weights/activations
    opt_bytes_per_param: int = 8    # AdamW f32 moments
    extra: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.intermediate is None:
            self.intermediate = 4 * self.hidden

    @property
    def params(self) -> float:
        per_layer = (4 * self.hidden * self.hidden          # qkvo
                     + 3 * self.hidden * self.intermediate)  # swiglu mlp
        return self.num_layers * per_layer + self.vocab * self.hidden


class AnalyticCostModel:
    def __init__(self, model: ModelDesc, hw: HardwareSpec | str = "v5e",
                 base_efficiency=0.5):
        self.m = model
        self.hw = HW_PRESETS[hw] if isinstance(hw, str) else hw
        self.base_eff = base_efficiency

    # ------------------------------ pieces -----------------------------------
    def _ring_allreduce_s(self, bytes_, n):
        if n <= 1 or bytes_ <= 0:
            return 0.0
        return (2 * (n - 1) / n) * bytes_ / self.hw.ici_bw \
            + (n - 1) * self.hw.ici_latency

    def _efficiency(self, mp):
        """Matmul efficiency falls as mp slices the contraction/output dims
        below the MXU tile; coarse but monotone (scaling-book shape rule)."""
        eff = self.base_eff
        min_dim = min(self.m.hidden, self.m.intermediate) / max(mp, 1)
        if min_dim < 128:
            eff *= min_dim / 128.0
        elif min_dim < 256:
            eff *= 0.85
        return max(eff, 1e-3)

    # ------------------------------ estimate ---------------------------------
    def estimate(self, cfg) -> dict:
        m, hw = self.m, self.hw
        dp = cfg.get("dp_degree", 1)
        mp = cfg.get("mp_degree", 1)
        pp = cfg.get("pp_degree", 1)
        sh = cfg.get("sharding_degree", 1)
        mbs = cfg.get("micro_batch_size", 1)
        chips = dp * mp * pp * sh

        local_batch = m.global_batch / (dp * sh)
        micro = max(1, int(math.ceil(local_batch / mbs)))
        tokens = m.global_batch * m.seq_len

        # -- memory feasibility (params sharded by mp x pp x sharding) --------
        p_local = m.params / (mp * pp * max(sh, 1))
        state = p_local * (m.dtype_bytes + m.dtype_bytes
                           + m.opt_bytes_per_param)
        act = (mbs * m.seq_len * m.hidden * m.dtype_bytes
               * (m.num_layers / pp) * 6 / mp)   # ~6 live tensors/layer
        logits = mbs * m.seq_len * m.vocab * 4 / mp if pp == 1 else 0
        mem = state + act + logits
        feasible = mem <= hw.hbm_bytes

        # -- compute ----------------------------------------------------------
        flops = tokens * (6 * m.params
                          + 12 * m.num_layers * m.hidden * m.seq_len)
        compute = flops / (chips * hw.peak_flops * self._efficiency(mp))

        # -- TP activation all-reduces ---------------------------------------
        act_bytes = mbs * m.seq_len * m.hidden * m.dtype_bytes
        per_micro = 4 * m.num_layers / pp * self._ring_allreduce_s(
            act_bytes, mp)
        tp_comm = per_micro * micro

        # -- gradient sync over dp x sharding ---------------------------------
        grad_sync = self._ring_allreduce_s(
            (m.params / (mp * pp)) * m.dtype_bytes, dp * sh)

        # -- pipeline ---------------------------------------------------------
        bubble = (pp - 1) / (micro + pp - 1) if pp > 1 else 0.0
        p2p = 0.0
        if pp > 1:
            p2p = 2 * (pp - 1) * micro * act_bytes / hw.ici_bw

        work = (compute + tp_comm) / max(1 - bubble, 1e-6) + p2p + grad_sync
        return {
            "step_time_s": work if feasible else float("inf"),
            "compute_s": compute, "tp_comm_s": tp_comm,
            "grad_sync_s": grad_sync, "p2p_s": p2p,
            "pp_bubble_frac": bubble,
            "mem_bytes_per_chip": mem, "feasible": feasible,
            "tokens_per_sec": (tokens / work) if feasible and work > 0 else 0.0,
        }

    def rank(self, cfgs) -> list:
        """Candidates ordered best-first by estimated step time (infeasible
        last); each gets an '_estimate' key attached."""
        scored = []
        for cfg in cfgs:
            est = self.estimate(cfg)
            scored.append({**cfg, "_estimate": est})
        scored.sort(key=lambda c: c["_estimate"]["step_time_s"])
        return scored
