"""Distributed auto-tuner (reference: python/paddle/distributed/auto_tuner —
tuner.py AutoTuner search_once/add_cfg, prune.py prune_by_mp/pp/mbs,
recorder.py history, search.py grid search).

TPU framing: candidates are hybrid-mesh layouts (dp, mp, pp, sharding, plus
micro-batch size) factorizing the chip count; pruning encodes TPU realities
(mp wants to stay inside a node's ICI domain, pp bounded by layer count,
global batch divisibility). The runner measures a real candidate by jitting
one train step on the mesh and timing it — the reference launches whole
trial jobs; on TPU one-process GSPMD makes in-process trials possible."""
from __future__ import annotations

import csv
import itertools
import os
import time

from .cost_model import (AnalyticCostModel, HardwareSpec, ModelDesc,  # noqa: F401
                         HW_PRESETS)

__all__ = ["AutoTuner", "candidate_configs", "Recorder",
           "AnalyticCostModel", "HardwareSpec", "ModelDesc", "HW_PRESETS"]


def _divisors(n):
    return [d for d in range(1, n + 1) if n % d == 0]


def candidate_configs(num_devices, num_layers=None, max_mp=8, max_pp=None,
                      global_batch=None, micro_batches=(1, 2, 4, 8)):
    """All (dp, mp, pp, sharding, mbs) with dp*mp*pp*sharding == devices,
    pruned (reference prune.py rules, TPU-flavored)."""
    out = []
    for mp, pp, sharding in itertools.product(_divisors(num_devices),
                                              repeat=3):
        rest = num_devices // (mp * pp * sharding)
        if mp * pp * sharding * rest != num_devices or rest < 1:
            continue
        dp = rest
        if mp > max_mp:                      # prune_by_mp: ICI domain bound
            continue
        if max_pp is not None and pp > max_pp:
            continue
        if num_layers is not None and pp > 1 and num_layers % pp != 0:
            continue                         # prune_by_pp: uneven stages
        for mbs in micro_batches:
            if global_batch is not None:
                if global_batch % (dp * sharding) != 0:
                    continue
                local = global_batch // (dp * sharding)
                if local % mbs != 0:         # prune_by_mbs
                    continue
            cfg = {"dp_degree": dp, "mp_degree": mp, "pp_degree": pp,
                   "sharding_degree": sharding, "micro_batch_size": mbs}
            if cfg not in out:
                out.append(cfg)
    # search order: less model-splitting first (reference sorts candidates)
    out.sort(key=lambda c: (c["pp_degree"], c["mp_degree"],
                            c["sharding_degree"], -c["micro_batch_size"]))
    return out


class Recorder:
    """History of (config, metric) trials (reference: recorder.py)."""

    def __init__(self):
        self.history = []

    def add_cfg(self, cfg, metric=None, error=None):
        self.history.append({**cfg, "metric": metric, "error": error})

    def sort_metric(self, direction="Maximize"):
        ok = [h for h in self.history if h.get("metric") is not None]
        ok.sort(key=lambda h: h["metric"], reverse=(direction == "Maximize"))
        return ok

    def store_history(self, path="./history.csv"):
        if not self.history:
            return path
        keys = list(self.history[0])
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=keys)
            w.writeheader()
            w.writerows(self.history)
        return path

    def load_history(self, path="./history.csv"):
        with open(path, newline="") as f:
            for row in csv.DictReader(f):
                self.history.append({
                    k: (None if v == "" else
                        float(v) if k == "metric" else
                        int(v) if v.lstrip("-").isdigit() else v)
                    for k, v in row.items()})


class AutoTuner:
    """reference: tuner.py:21 — iterate search_once()/add_cfg until
    candidates are exhausted, then best_cfg."""

    def __init__(self, tuner_cfg, cost_model=None):
        """cost_model: optional AnalyticCostModel. When given, candidates are
        RANKED by estimated step time before any trial runs, infeasible
        layouts (per-chip memory over HBM) are dropped, and
        tuner_cfg['prune_to'] keeps only the top-K — the reference's
        estimate_cost.py pre-pruning, which a purely empirical tuner cannot
        do (it cannot rank candidates it never runs)."""
        self.cfg = dict(tuner_cfg)
        self.recorder = Recorder()
        self._candidates = candidate_configs(
            num_devices=self.cfg.get("num_devices") or
            self.cfg.get("num_gpus", 1),
            num_layers=self.cfg.get("num_layers"),
            max_mp=self.cfg.get("max_mp_degree", 8),
            max_pp=self.cfg.get("max_pp_degree"),
            global_batch=self.cfg.get("global_batch_size"),
            micro_batches=tuple(self.cfg.get("micro_batches", (1, 2, 4, 8))))
        self.cost_model = cost_model
        if cost_model is not None:
            ranked = cost_model.rank(self._candidates)
            ranked = [c for c in ranked if c["_estimate"]["feasible"]]
            prune_to = self.cfg.get("prune_to")
            if prune_to:
                ranked = ranked[:int(prune_to)]
            self._candidates = ranked
        self._idx = 0
        self.direction = self.cfg.get("direction", "Maximize")

    @property
    def search_space_size(self):
        return len(self._candidates)

    def search_once(self):
        if self._idx >= len(self._candidates):
            return None
        cfg = self._candidates[self._idx]
        self._idx += 1
        return cfg

    def add_cfg(self, cfg, metric=None, error=None):
        self.recorder.add_cfg(cfg, metric=metric, error=error)

    def best_cfg(self):
        ranked = self.recorder.sort_metric(self.direction)
        return ranked[0] if ranked else None

    # -- in-process trial runner (TPU one-process GSPMD) ----------------------
    def run_trials(self, make_step, warmup=1, iters=3, log=None):
        """make_step(cfg) -> zero-arg callable running ONE train step on the
        cfg's mesh (raises on invalid layouts). Times each candidate and
        records steps/sec."""
        while True:
            cfg = self.search_once()
            if cfg is None:
                break
            try:
                step = make_step(cfg)
                for _ in range(warmup):
                    step()
                t0 = time.perf_counter()
                for _ in range(iters):
                    step()
                dt = (time.perf_counter() - t0) / iters
                self.add_cfg(cfg, metric=1.0 / dt)
                if log:
                    log(f"trial {cfg}: {1.0 / dt:.2f} steps/s")
            except Exception as e:          # OOM / invalid layout: record
                self.add_cfg(cfg, error=str(e))
                if log:
                    log(f"trial {cfg}: failed ({e})")
        return self.best_cfg()
