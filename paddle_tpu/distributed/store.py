"""TCPStore — rendezvous/control-plane key-value store (reference:
phi/core/distributed/store/tcp_store.h:121 + tcp_utils; python surface
paddle.distributed.TCPStore).

The master rank hosts a tiny threaded socket server; every rank (master
included) connects as a client. Values are opaque bytes; `get` blocks until
the key exists (the reference's Wait semantics). This is the control plane
only — bulk tensor traffic rides XLA collectives, not this store."""
from __future__ import annotations

import pickle
import socket
import struct
import threading
import time


def _send_msg(sock, obj):
    data = pickle.dumps(obj)
    sock.sendall(struct.pack("!I", len(data)) + data)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            raise ConnectionError("store connection closed")
        hdr += chunk
    (n,) = struct.unpack("!I", hdr)
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("store connection closed")
        buf += chunk
    return pickle.loads(buf)


class _StoreServer(threading.Thread):
    def __init__(self, host, port):
        super().__init__(daemon=True)
        self._kv = {}
        self._cv = threading.Condition()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(128)
        self.port = self._srv.getsockname()[1]

    def run(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                cmd, key, val, timeout = _recv_msg(conn)
                if cmd == "set":
                    with self._cv:
                        self._kv[key] = val
                        self._cv.notify_all()
                    _send_msg(conn, ("ok", None))
                elif cmd == "get":
                    deadline = time.time() + timeout
                    with self._cv:
                        while key not in self._kv:
                            left = deadline - time.time()
                            if left <= 0:
                                break
                            self._cv.wait(left)
                        if key in self._kv:
                            _send_msg(conn, ("ok", self._kv[key]))
                        else:
                            _send_msg(conn, ("timeout", None))
                elif cmd == "add":
                    with self._cv:
                        cur = int(self._kv.get(key, 0)) + int(val)
                        self._kv[key] = cur
                        self._cv.notify_all()
                    _send_msg(conn, ("ok", cur))
                elif cmd == "delete":
                    with self._cv:
                        existed = self._kv.pop(key, None) is not None
                        self._cv.notify_all()
                    _send_msg(conn, ("ok", existed))
                elif cmd == "wait":
                    deadline = time.time() + timeout
                    ok = True
                    with self._cv:
                        for k in key:       # key is a list here
                            while k not in self._kv:
                                left = deadline - time.time()
                                if left <= 0:
                                    ok = False
                                    break
                                self._cv.wait(left)
                    _send_msg(conn, ("ok" if ok else "timeout", None))
                else:
                    _send_msg(conn, ("badcmd", None))
        except (ConnectionError, EOFError, OSError):
            pass
        finally:
            conn.close()


class TCPStore:
    """Client handle; rank `is_master` also hosts the server in-process."""

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=300.0):
        self.timeout = timeout
        self._server = None
        if is_master:
            self._server = _StoreServer(host if host != "127.0.0.1" else
                                        "0.0.0.0", port)
            self._server.start()
            port = self._server.port
        self.host, self.port = host, port
        deadline = time.time() + timeout
        last = None
        while True:
            try:
                self._sock = socket.create_connection((host, port), timeout=5)
                break
            except OSError as e:
                last = e
                if time.time() > deadline:
                    raise TimeoutError(
                        f"could not reach TCPStore at {host}:{port}") from last
                time.sleep(0.1)
        self._lock = threading.Lock()

    def _rpc(self, cmd, key, val=None, timeout=None):
        with self._lock:
            _send_msg(self._sock, (cmd, key, val,
                                   self.timeout if timeout is None else timeout))
            status, out = _recv_msg(self._sock)
        if status == "timeout":
            raise TimeoutError(f"TCPStore {cmd}({key!r}) timed out")
        if status != "ok":
            raise RuntimeError(f"TCPStore error: {status}")
        return out

    def set(self, key, value):
        self._rpc("set", key, value)

    def get(self, key, timeout=None):
        return self._rpc("get", key, timeout=timeout)

    def add(self, key, amount=1):
        return self._rpc("add", key, amount)

    def delete_key(self, key):
        return self._rpc("delete", key)

    def wait(self, keys, timeout=None):
        self._rpc("wait", list(keys), timeout=timeout)
