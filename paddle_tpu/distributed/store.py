"""TCPStore — rendezvous/control-plane key-value store (reference:
phi/core/distributed/store/tcp_store.h:121 MasterDaemon + tcp_utils; python
surface paddle.distributed.TCPStore).

Like the reference, the daemon is NATIVE C++ (core/native/store.cc, compiled
on first use): the master rank hosts it in-process and every rank (master
included) connects as a client speaking a tiny length-prefixed binary
protocol. A pure-Python server with the identical protocol is the fallback
when no toolchain is available. Values are opaque bytes (objects pickle
transparently in the client); `get` blocks until the key exists (the
reference's Wait semantics). This is the control plane only — bulk tensor
traffic rides XLA collectives, not this store.

Wire protocol (see store.cc):
  request : u8 cmd | u32 klen | key | u32 vlen | val | f64 timeout   (BE)
  response: u8 status (0 ok, 1 timeout, 2 bad, 3 deleted-miss) | u32 vlen | val
  cmds: 1 SET  2 GET  3 ADD (val = i64 BE)  4 DELETE  5 WAIT ('\n'-joined)
        6 CAS (val = u32 elen | expected | desired; elen 0 = expect-absent;
               reply val = u8 swapped | current bytes)

Lease-grade primitives (membership.py is the consumer):

- ``compare_and_set`` is an atomic read-modify-write on one key — the
  index-set updates of the membership plane ride it instead of a racy
  get+set.  The ``expected`` side compares RAW stored bytes (what
  ``get_raw`` returned), never a re-pickle: pickling a ``set`` is not
  byte-stable across processes, so value-level comparison would livelock.
- A blocking GET that observes the key being DELETEd mid-wait returns a
  typed miss (status 3 -> :class:`StoreKeyDeleted`) immediately instead of
  hanging until its timeout: a watcher reading a member key that the member
  just released sees a clean "gone", not a stall.
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

from ..core.retry import RetryError, RetryPolicy, retry_call
from ..testing.faults import FAULTS as _faults
from ..testing.faults import InjectedFault as _InjectedFault

_SET, _GET, _ADD, _DELETE, _WAIT, _CAS = 1, 2, 3, 4, 5, 6


class StoreKeyDeleted(KeyError):
    """A blocking read observed its key being deleted mid-wait (server
    status 3) — typed so callers can distinguish "released cleanly" from
    "never appeared" (:class:`TimeoutError`)."""

    def __init__(self, key):
        super().__init__(key)
        self.key = key


def _pack_req(cmd, key, val, timeout):
    k = key.encode() if isinstance(key, str) else key
    return (struct.pack("!B", cmd) + struct.pack("!I", len(k)) + k +
            struct.pack("!I", len(val)) + val + struct.pack("!d", timeout))


def _read_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store connection closed")
        buf += chunk
    return buf


def _read_reply(sock):
    status = _read_exact(sock, 1)[0]
    (n,) = struct.unpack("!I", _read_exact(sock, 4))
    val = _read_exact(sock, n) if n else b""
    return status, val


class _PyStoreServer(threading.Thread):
    """Python fallback daemon speaking the same binary protocol."""

    def __init__(self, host, port):
        super().__init__(daemon=True)
        self._kv = {}
        self._dels = {}        # key -> deletion generation (see GET/DELETE)
        self._cv = threading.Condition()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._srv.bind((host, port))
            self._srv.listen(128)
            self.port = self._srv.getsockname()[1]
        except OSError:
            # bind failure (EADDRINUSE on master restart) must not leak
            # the listener fd: the caller never gets a server to stop
            self._srv.close()
            raise

    def run(self):
        while True:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _reply(self, conn, status, val=b""):
        conn.sendall(struct.pack("!B", status) + struct.pack("!I", len(val))
                     + val)

    def _serve(self, conn):
        try:
            while True:
                cmd = _read_exact(conn, 1)[0]
                (kn,) = struct.unpack("!I", _read_exact(conn, 4))
                key = _read_exact(conn, kn).decode()
                (vn,) = struct.unpack("!I", _read_exact(conn, 4))
                val = _read_exact(conn, vn) if vn else b""
                (timeout,) = struct.unpack("!d", _read_exact(conn, 8))
                if cmd == _SET:
                    with self._cv:
                        self._kv[key] = val
                        self._cv.notify_all()
                    self._reply(conn, 0)
                elif cmd == _GET:
                    deadline = time.monotonic() + timeout
                    with self._cv:
                        # a DELETE processed while we wait bumps the key's
                        # deletion generation: reply a typed miss (status 3)
                        # immediately instead of stalling to the timeout
                        gen0 = self._dels.get(key, 0)
                        deleted = False
                        while key not in self._kv:
                            if self._dels.get(key, 0) != gen0:
                                deleted = True
                                break
                            left = deadline - time.monotonic()
                            if left <= 0:
                                break
                            self._cv.wait(left)
                        out = self._kv.get(key)
                    # reply OUTSIDE the lock like every other command path:
                    # sendall to one slow client must not stall the whole
                    # store (every GET/SET/WAIT contends on this condition)
                    if out is not None:
                        self._reply(conn, 0, out)
                    else:
                        self._reply(conn, 3 if deleted else 1)
                elif cmd == _ADD:
                    (delta,) = struct.unpack("!q", val)
                    with self._cv:
                        cur = int(self._kv.get(key, b"0")) + delta
                        self._kv[key] = str(cur).encode()
                        self._cv.notify_all()
                    self._reply(conn, 0, struct.pack("!q", cur))
                elif cmd == _DELETE:
                    with self._cv:
                        existed = self._kv.pop(key, None) is not None
                        self._dels[key] = self._dels.get(key, 0) + 1
                        self._cv.notify_all()
                    self._reply(conn, 0, b"1" if existed else b"0")
                elif cmd == _CAS:
                    (en,) = struct.unpack("!I", val[:4])
                    expected, desired = val[4:4 + en], val[4 + en:]
                    with self._cv:
                        cur = self._kv.get(key)
                        swapped = (cur is None) if en == 0 else (cur == expected)
                        if swapped:
                            self._kv[key] = desired
                            cur = desired
                            self._cv.notify_all()
                    self._reply(conn, 0, (b"\x01" if swapped else b"\x00")
                                + (cur or b""))
                elif cmd == _WAIT:
                    deadline = time.monotonic() + timeout
                    ok = True
                    with self._cv:
                        for k in key.split("\n") if key else []:
                            while k not in self._kv:
                                left = deadline - time.monotonic()
                                if left <= 0:
                                    ok = False
                                    break
                                self._cv.wait(left)
                            if not ok:
                                break
                    self._reply(conn, 0 if ok else 1)
                else:
                    self._reply(conn, 2)
        except (ConnectionError, EOFError, OSError, struct.error):
            pass
        finally:
            conn.close()


def _start_server(host, port):
    """Prefer the native C++ daemon; fall back to the Python thread.
    Returns (bound_port, server_kind)."""
    if os.environ.get("PADDLE_TPU_PURE_PY_STORE") != "1":
        from ..core.native.build import load
        lib = load("pt_store", "store.cc")
        if lib is not None:
            import ctypes
            lib.pt_store_start.restype = ctypes.c_int
            lib.pt_store_start.argtypes = [ctypes.c_char_p, ctypes.c_int]
            bound = lib.pt_store_start(host.encode(), port)
            if bound > 0:
                return bound, "native"
    srv = _PyStoreServer(host, port)
    srv.start()
    return srv.port, "python"


class TCPStore:
    """Client handle; rank `is_master` also hosts the daemon in-process."""

    def __init__(self, host="127.0.0.1", port=0, is_master=False,
                 world_size=1, timeout=300.0):
        self.timeout = timeout
        self.server_kind = None
        if is_master:
            bind = "0.0.0.0" if host == "127.0.0.1" else host
            port, self.server_kind = _start_server(bind, port)
        self.host, self.port = host, port
        # shared backoff-with-jitter policy (core/retry.py) instead of the
        # old flat 0.1s spin: a whole cohort connecting to a master that is
        # still binding decorrelates instead of stampeding.  The deadline
        # keeps the former `timeout` contract.
        policy = RetryPolicy(max_attempts=64, base_delay=0.05, max_delay=1.0,
                             deadline=timeout)
        try:
            self._sock = retry_call(self._connect, policy=policy,
                                    retry_on=(OSError, _InjectedFault),
                                    op="store.connect")
        except RetryError as e:
            raise TimeoutError(
                f"could not reach TCPStore at {host}:{port}") from e.__cause__
        self._lock = threading.Lock()

    def _connect(self):
        _faults.maybe_fire("store.connect", host=self.host, port=self.port)
        sock = socket.create_connection((self.host, self.port), timeout=5)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # blocking get/wait time out SERVER-side (protocol timeout
            # field); the connect timeout must not cap recv
            sock.settimeout(None)
        except OSError:
            sock.close()
            raise
        return sock

    def _rpc(self, cmd, key, val=b"", timeout=None):
        t = self.timeout if timeout is None else timeout
        with self._lock:
            # the server enforces t; the socket deadline is a dead-server
            # backstop with generous grace.  Socket I/O under _lock is the
            # lock's whole purpose: one shared socket, one in-flight RPC —
            # request/reply framing would interleave without it.
            self._sock.settimeout(t + 30)
            self._sock.sendall(  # graftlint: disable=concurrency
                _pack_req(cmd, key, val, t))
            status, out = _read_reply(self._sock)
        if status == 1:
            raise TimeoutError(f"TCPStore cmd {cmd} ({key!r}) timed out")
        if status == 3:
            raise StoreKeyDeleted(key)
        if status != 0:
            raise RuntimeError(f"TCPStore error status {status}")
        return out

    def set(self, key, value):
        self._rpc(_SET, key, pickle.dumps(value))

    def get(self, key, timeout=None):
        raw = self._rpc(_GET, key, timeout=timeout)
        try:
            return pickle.loads(raw)
        except Exception:
            # keys written by add() hold ASCII decimal (the C++ daemon does
            # arithmetic on them); surface those as ints like the reference
            try:
                return int(raw)
            except ValueError:
                return raw

    def get_raw(self, key, timeout=None):
        """Blocking read returning the EXACT stored bytes — the token
        :meth:`compare_and_set` compares against.  Same wait semantics and
        typed errors as :meth:`get`."""
        return self._rpc(_GET, key, timeout=timeout)

    def compare_and_set(self, key, expected, desired):
        """Atomic swap: install ``desired`` iff the key's current raw bytes
        equal ``expected``.  ``expected`` is the raw bytes a prior
        :meth:`get_raw` returned, or None to mean "key must be absent" (raw
        bytes, not a re-pickle: pickling is not byte-stable across
        processes).  ``desired`` is pickled unless already bytes.  Returns
        ``(swapped, current_raw)`` where ``current_raw`` is the stored bytes
        after the operation (None when the key is absent)."""
        if expected is not None and not isinstance(expected, bytes):
            raise TypeError("expected must be raw bytes from get_raw(), "
                            "or None for expect-absent")
        want = b"" if expected is None else expected
        if expected == b"":
            raise ValueError("empty expected bytes are reserved for "
                             "expect-absent (pass None)")
        if not isinstance(desired, bytes):
            desired = pickle.dumps(desired)
        out = self._rpc(_CAS, key,
                        struct.pack("!I", len(want)) + want + desired)
        return out[:1] == b"\x01", (out[1:] or None)

    def add(self, key, amount=1):
        out = self._rpc(_ADD, key, struct.pack("!q", int(amount)))
        return struct.unpack("!q", out)[0]

    def delete_key(self, key):
        return self._rpc(_DELETE, key) == b"1"

    def wait(self, keys, timeout=None):
        self._rpc(_WAIT, "\n".join(keys), timeout=timeout)
