"""Parameter-server mode (reference: the reference's PS stack —
python/paddle/distributed/fleet runtime with pslib/the_one_ps servers —
reduced to its TPU-relevant core).

TPU framing: dense training scales via data/model parallelism on XLA
collectives, so the PS here serves the genuinely PS-shaped workload the
reference keeps PS for: host-resident sparse embedding tables too large for
HBM. Servers hold named numpy tables sharded by row-hash; trainers pull rows
/ push sparse row gradients over the RPC agent (control-plane sockets)."""
from __future__ import annotations

import threading

import numpy as np

from . import rpc

__all__ = ["ParameterServer", "PsClient", "row_shard"]

_tables: dict[str, "_Table"] = {}
_tables_lock = threading.Lock()


class _Table:
    def __init__(self, rows, dim, initializer="zeros", lr=0.1,
                 optimizer="sgd"):
        if initializer == "zeros":
            self.data = np.zeros((rows, dim), np.float32)
        else:
            rng = np.random.RandomState(0)
            self.data = (rng.rand(rows, dim).astype(np.float32) - 0.5) * 0.02
        self.lr = lr
        self.optimizer = optimizer
        self.accum = np.zeros((rows,), np.float32) if optimizer == "adagrad" \
            else None
        self.lock = threading.Lock()


# ---- server-side handlers (execute on the PS rank via rpc) ------------------
def _ps_create(name, rows, dim, initializer, lr, optimizer):
    with _tables_lock:
        if name not in _tables:
            _tables[name] = _Table(rows, dim, initializer, lr, optimizer)
    return True


def _ps_pull(name, row_ids):
    t = _tables[name]
    with t.lock:
        return t.data[np.asarray(row_ids)]


def _ps_push(name, row_ids, grads):
    """Sparse update: rows row_ids -= lr * grads (duplicate ids accumulate)."""
    t = _tables[name]
    ids = np.asarray(row_ids)
    g = np.asarray(grads, np.float32)
    with t.lock:
        if t.optimizer == "adagrad":
            sq = np.zeros_like(t.accum)
            np.add.at(sq, ids, (g * g).mean(-1))
            t.accum += sq
            scale = t.lr / (np.sqrt(t.accum[ids]) + 1e-8)
            upd = np.zeros_like(t.data)
            np.add.at(upd, ids, g * scale[:, None])
        else:
            upd = np.zeros_like(t.data)
            np.add.at(upd, ids, t.lr * g)
        t.data -= upd
    return True


def _ps_stats(name):
    t = _tables[name]
    with t.lock:
        return {"shape": list(t.data.shape), "norm": float(
            np.linalg.norm(t.data))}


def row_shard(row_ids, num_servers):
    """row id -> server index (hash sharding, reference table sharding)."""
    return np.asarray(row_ids) % num_servers


class ParameterServer:
    """The PS rank: just keeps the process alive serving RPC handlers
    (reference: fleet.init_server()/run_server())."""

    def run(self):
        return  # the rpc agent thread serves; nothing else to do


class PsClient:
    """Trainer-side handle to a set of PS ranks (reference: fleet PS client
    via _communicator; pull/push sparse)."""

    def __init__(self, server_names):
        self.servers = list(server_names)

    def create_table(self, name, rows, dim, initializer="uniform", lr=0.1,
                     optimizer="sgd"):
        for s in self.servers:
            rpc.rpc_sync(s, _ps_create, args=(name, rows, dim, initializer,
                                              lr, optimizer))

    def _split(self, row_ids):
        ids = np.asarray(row_ids)
        shard = row_shard(ids, len(self.servers))
        parts = []
        for si in range(len(self.servers)):
            mask = shard == si
            parts.append((si, np.nonzero(mask)[0], ids[mask]))
        return parts

    def pull(self, name, row_ids, dim=None):
        ids = np.asarray(row_ids)
        out = None
        futs = []
        for si, pos, sub in self._split(ids):
            if len(sub) == 0:
                continue
            futs.append((pos, rpc.rpc_async(self.servers[si], _ps_pull,
                                            args=(name, sub))))
        for pos, f in futs:
            rows = f.result()
            if out is None:
                out = np.zeros((len(ids), rows.shape[1]), np.float32)
            out[pos] = rows
        return out

    def push(self, name, row_ids, grads):
        futs = []
        g = np.asarray(grads, np.float32)
        for si, pos, sub in self._split(row_ids):
            if len(sub) == 0:
                continue
            futs.append(rpc.rpc_async(self.servers[si], _ps_push,
                                      args=(name, sub, g[pos])))
        for f in futs:
            f.result()

    def stats(self, name):
        return [rpc.rpc_sync(s, _ps_stats, args=(name,))
                for s in self.servers]
