"""Collective watchdog — hang/failure detection for the comm plane
(reference: phi/core/distributed/comm_task_manager.h:37, .cc:141-273 — a
background thread that tracks in-flight collectives and aborts/logs when one
exceeds its timeout).

On TPU most collectives are compiled into XLA programs, so the watchable
surface is the explicit host-side collective API + blocking device fetches.
Every explicit collective in distributed/collective.py registers here when the
watchdog is enabled (FLAGS enable_comm_watchdog or enable())."""
from __future__ import annotations

import functools
import threading
import time
import traceback


class CommTask:
    __slots__ = ("name", "rank", "start", "timeout", "done", "stack", "seq")

    def __init__(self, name, rank, timeout, seq):
        self.name = name
        self.rank = rank
        self.start = time.monotonic()
        self.timeout = timeout
        self.done = False
        self.seq = seq
        self.stack = traceback.format_stack(limit=8)


class CommTaskManager:
    """Singleton watchdog (reference CommTaskManager::GetInstance)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self, default_timeout=600.0, poll_interval=1.0):
        self.default_timeout = default_timeout
        self.poll_interval = poll_interval
        self._tasks = {}
        self._seq = 0
        self._mu = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self.timed_out: list[CommTask] = []
        self.on_timeout = self._default_handler
        self.enabled = False

    @classmethod
    def instance(cls) -> "CommTaskManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = CommTaskManager()
            return cls._instance

    # ---- lifecycle ------------------------------------------------------------
    def enable(self, timeout=None, on_timeout=None, poll_interval=None):
        if timeout is not None:
            self.default_timeout = timeout
        if on_timeout is not None:
            self.on_timeout = on_timeout
        if poll_interval is not None:
            self.poll_interval = poll_interval
        self.enabled = True
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._watch, daemon=True,
                                            name="comm-watchdog")
            self._thread.start()

    def disable(self):
        self.enabled = False
        self._stop.set()

    # ---- task tracking ----------------------------------------------------------
    def begin(self, name, rank=0, timeout=None) -> int:
        if not self.enabled:
            return -1
        with self._mu:
            self._seq += 1
            seq = self._seq
            self._tasks[seq] = CommTask(name, rank,
                                        timeout or self.default_timeout, seq)
        return seq

    def end(self, seq: int):
        if seq < 0:
            return
        with self._mu:
            t = self._tasks.pop(seq, None)
            if t is not None:
                t.done = True

    def in_flight(self):
        with self._mu:
            return list(self._tasks.values())

    # ---- watchdog loop ----------------------------------------------------------
    def _watch(self):
        while not self._stop.wait(self.poll_interval):
            now = time.monotonic()
            expired = []
            with self._mu:
                for seq, t in list(self._tasks.items()):
                    if now - t.start > t.timeout:
                        expired.append(t)
                        del self._tasks[seq]
            for t in expired:
                self.timed_out.append(t)
                self._count_timeout(t)
                try:
                    self.on_timeout(t)
                except Exception:
                    traceback.print_exc()

    @staticmethod
    def _count_timeout(task: CommTask):
        """Mirror the expiry into ``comm_watchdog_timeouts_total{op=...}``
        so dashboards see probable hangs without scraping stderr."""
        from .. import observability as _obs
        if _obs.enabled():
            _obs.COMM_WATCHDOG_TIMEOUTS.labels(op=task.name).inc()

    @staticmethod
    def _default_handler(task: CommTask):
        import sys
        # graftlint: disable-next-line — deliberate stderr on a probable
        # hang: must not depend on user logging config
        print(f"[comm-watchdog] collective "  # graftlint: disable=no-adhoc-telemetry
              f"'{task.name}' (rank {task.rank}) "
              f"exceeded {task.timeout:.0f}s — probable hang. Issued from:\n"
              + "".join(task.stack), file=sys.stderr, flush=True)


def watched(fn):
    """Decorator: track an explicit collective in the watchdog."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        mgr = CommTaskManager.instance()
        if not mgr.enabled:
            return fn(*args, **kwargs)
        from .env import get_rank
        seq = mgr.begin(fn.__name__, rank=get_rank())
        try:
            return fn(*args, **kwargs)
        finally:
            mgr.end(seq)
    return wrapper
