"""Placement types (reference: paddle/phi/core/distributed/auto_parallel/
placement_types.h; python surface paddle.distributed.{Replicate,Shard,Partial}).

Maps 1:1 onto GSPMD: Shard(d) on mesh axis a ⇒ PartitionSpec dim d = a;
Replicate ⇒ None; Partial ⇒ unreduced pending-sum (materialized as replicated
storage + a pending reduce op, like the reference's partial status)."""
from __future__ import annotations


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)

    def __hash__(self):
        return hash("Replicate")


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def is_shard(self, dim=None):
        return dim is None or dim == self.dim

    def get_dim(self):
        return self.dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim

    def __hash__(self):
        return hash(("Shard", self.dim))


class Partial(Placement):
    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __repr__(self):
        return f"Partial(reduce_type={self.reduce_type})"

    def __eq__(self, other):
        return isinstance(other, Partial) and other.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("Partial", self.reduce_type))


def placements_to_spec(placements, ndim, dim_names):
    """[Placement per mesh axis] -> jax PartitionSpec entries per tensor dim."""
    from jax.sharding import PartitionSpec as P
    entries: list = [None] * ndim
    for axis_idx, pl in enumerate(placements):
        if isinstance(pl, Shard):
            d = pl.dim % ndim
            if entries[d] is None:
                entries[d] = dim_names[axis_idx]
            elif isinstance(entries[d], tuple):
                entries[d] = entries[d] + (dim_names[axis_idx],)
            else:
                entries[d] = (entries[d], dim_names[axis_idx])
    return P(*entries)


def spec_to_placements(spec, mesh_dim_names, ndim):
    """PartitionSpec -> [Placement per mesh axis]."""
    placements = [Replicate() for _ in mesh_dim_names]
    for tdim, entry in enumerate(tuple(spec) + (None,) * (ndim - len(tuple(spec)))):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for a in axes:
            placements[mesh_dim_names.index(a)] = Shard(tdim)
    return placements
