"""Semi-auto parallel API (reference: python/paddle/distributed/auto_parallel/
api.py — shard_tensor:220, reshard:733, shard_layer:844; process_mesh.py:85;
C++ DistTensor phi/core/distributed/auto_parallel/dist_tensor.h:39).

TPU-native: a DistTensor is just a Tensor whose jax.Array carries a
NamedSharding over a jax.sharding.Mesh; reshard is device_put (eager) or
with_sharding_constraint (traced); sharding propagation is XLA GSPMD — the 115
hand-written spmd rules of the reference collapse into the compiler.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from ...core.dispatch import unwrap, _state
from .placement import (Placement, Replicate, Shard, Partial, placements_to_spec,
                        spec_to_placements)


class ProcessMesh:
    """reference: distributed/auto_parallel/process_mesh.py:85."""

    def __init__(self, mesh, dim_names=None, shape=None, process_ids=None):
        arr = np.asarray(mesh, dtype=np.int64)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        self._ids = arr
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self):
        return list(self._ids.shape)

    @property
    def ndim(self):
        return self._ids.ndim

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def process_ids(self):
        return self._ids.reshape(-1).tolist()

    def get_dim_size(self, name):
        return self._ids.shape[self._dim_names.index(name)]

    def get_mesh_with_dim(self, name, index=None):
        """Submesh along an axis (reference: process_mesh.py get_mesh_with_dim)."""
        axis = self._dim_names.index(name)
        moved = np.moveaxis(self._ids, axis, 0)
        names = [name] + [n for n in self._dim_names if n != name]
        if index is not None:
            return ProcessMesh(moved[index], names[1:])
        return ProcessMesh(moved, names)

    def get_group(self, dim_name=None):
        from ..collective import new_group
        return new_group(self.process_ids)

    @classmethod
    def from_jax_mesh(cls, jmesh: Mesh) -> "ProcessMesh":
        """Wrap an existing jax.sharding.Mesh, deriving process ids from the
        actual device array (preserves permuted / topology-aware layouts —
        rebuilding from np.arange would silently reorder devices)."""
        ids = np.vectorize(lambda d: d.id, otypes=[np.int64])(jmesh.devices)
        pm = cls(ids, list(jmesh.axis_names))
        pm._jax_mesh = jmesh
        return pm

    def jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devices = np.asarray(jax.devices(), dtype=object)
            dev_arr = np.empty(self._ids.shape, dtype=object)
            flat_ids = self._ids.reshape(-1)
            dev_flat = [devices[i] for i in flat_ids]
            dev_arr = np.asarray(dev_flat, dtype=object).reshape(self._ids.shape)
            self._jax_mesh = Mesh(dev_arr, axis_names=tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh) and
                np.array_equal(self._ids, other._ids) and
                self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._ids.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self._dim_names})"


_global_mesh = None


def set_mesh(mesh: ProcessMesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> ProcessMesh:
    return _global_mesh


def _norm_placements(placements, mesh: ProcessMesh):
    if placements is None:
        return [Replicate() for _ in range(mesh.ndim)]
    out = list(placements)
    while len(out) < mesh.ndim:
        out.append(Replicate())
    return out


def _sharding_for(mesh: ProcessMesh, placements, ndim) -> NamedSharding:
    spec = placements_to_spec(placements, ndim, mesh.dim_names)
    return NamedSharding(mesh.jax_mesh(), spec)


def shard_tensor(data, mesh: ProcessMesh, placements, dtype=None, place=None,
                 stop_gradient=None):
    """reference: auto_parallel/api.py:220 — returns a DistTensor (here: a Tensor
    whose array is device_put with a NamedSharding)."""
    t = data if isinstance(data, Tensor) else Tensor(jnp.asarray(np.asarray(data)))
    placements = _norm_placements(placements, mesh)
    sharding = _sharding_for(mesh, placements, t.ndim)
    partial_axes = [i for i, p in enumerate(placements) if isinstance(p, Partial)]
    if _state.trace_ctx is not None or isinstance(t._data, jax.core.Tracer):
        arr = jax.lax.with_sharding_constraint(unwrap(t), sharding)
    else:
        arr = jax.device_put(unwrap(t), sharding)
    out = Tensor(arr, stop_gradient=t.stop_gradient if stop_gradient is None else stop_gradient)
    out._grad_node, out._out_slot = t._grad_node, t._out_slot
    _set_dist_attr(out, mesh, placements)
    return out


def _set_dist_attr(t: Tensor, mesh, placements):
    # Tensor uses __slots__; dist attrs ride on the array's sharding + a registry
    _dist_attrs[id(t)] = (mesh, list(placements))


_dist_attrs: dict = {}


def get_placements(t: Tensor):
    if id(t) in _dist_attrs:
        return _dist_attrs[id(t)][1]
    sharding = getattr(t._data, "sharding", None)
    if isinstance(sharding, NamedSharding):
        mesh_names = list(sharding.mesh.axis_names)
        return spec_to_placements(sharding.spec, mesh_names, t.ndim)
    return None


def get_process_mesh(t: Tensor):
    if id(t) in _dist_attrs:
        return _dist_attrs[id(t)][0]
    sharding = getattr(t._data, "sharding", None)
    if isinstance(sharding, NamedSharding):
        m = sharding.mesh
        ids = np.arange(np.prod(m.devices.shape)).reshape(m.devices.shape)
        return ProcessMesh(ids, list(m.axis_names))
    return None


def reshard(dist_tensor, mesh: ProcessMesh, placements):
    """reference: auto_parallel/api.py:733 + the C++ reshard function library
    (phi/core/distributed/auto_parallel/reshard/) — all transitions (r_to_s,
    s_to_r, p_to_r, s_to_s, cross-mesh) collapse into one device_put /
    sharding_constraint; XLA emits the collectives."""
    placements = _norm_placements(placements, mesh)
    sharding = _sharding_for(mesh, placements, dist_tensor.ndim)
    arr = unwrap(dist_tensor)
    if _state.trace_ctx is not None or isinstance(arr, jax.core.Tracer):
        out_arr = jax.lax.with_sharding_constraint(arr, sharding)
    else:
        out_arr = jax.device_put(arr, sharding)
    out = Tensor(out_arr, stop_gradient=dist_tensor.stop_gradient)
    out._grad_node, out._out_slot = dist_tensor._grad_node, dist_tensor._out_slot
    _set_dist_attr(out, mesh, placements)
    return out


def shard_layer(layer, process_mesh: ProcessMesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """reference: auto_parallel/api.py:844 — shard every parameter of a Layer."""
    def default_shard(name, sublayer, mesh):
        for pname, p in list(sublayer._parameters.items()):
            if p is None:
                continue
            sharded = shard_tensor(p, mesh, [Replicate() for _ in range(mesh.ndim)])
            p._data = sharded._data
    fn = shard_fn or default_shard
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(lambda l, inp: input_fn(inp, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(lambda l, inp, out: output_fn(out, process_mesh))
    return layer


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def unshard_dtensor(dist_tensor):
    """Gather to replicated (reference: auto_parallel/api.py unshard_dtensor)."""
    arr = unwrap(dist_tensor)
    sharding = getattr(arr, "sharding", None)
    if isinstance(sharding, NamedSharding):
        out = jax.device_put(arr, NamedSharding(sharding.mesh, P()))
        t = Tensor(out, stop_gradient=dist_tensor.stop_gradient)
        return t
    return dist_tensor


def shard_optimizer(optimizer, shard_fn=None):
    """reference: auto_parallel/api.py shard_optimizer — accumulators follow the
    parameter shardings automatically on first access (our accumulators are
    created zeros_like the param, inheriting its sharding under jit)."""
    return optimizer


def local_map(fn, out_placements=None, in_placements=None, process_mesh=None,
              reshard_inputs=False):
    """Run fn on local shards via shard_map (reference: auto_parallel local_map)."""
    def wrapper(*tensors):
        from ...parallel._compat import shard_map
        mesh = (process_mesh or _global_mesh).jax_mesh()
        in_specs = tuple(placements_to_spec(p, t.ndim, list(mesh.axis_names))
                         for p, t in zip(in_placements, tensors))
        out_specs = placements_to_spec(out_placements[0], tensors[0].ndim,
                                       list(mesh.axis_names))
        f = shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        return Tensor(f(*[unwrap(t) for t in tensors]))
    return wrapper
