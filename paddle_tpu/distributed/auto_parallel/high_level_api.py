"""to_distributed — fully-automatic placement (reference: python/paddle/
distributed/auto_parallel/high_level_api.py:253).

The reference analyzes the model structure and picks a parallelization plan
(TP for attention/MLP projections, vocab-sharded embeddings, DP/sharding for
the rest). TPU-native: the same structural heuristics, realized as
NamedSharding placements; GSPMD does the rest. Deterministic and inspectable:
returns the applied plan alongside the model via `model._dist_plan`.
"""
from __future__ import annotations

import re

from .api import ProcessMesh, get_mesh
from .intermediate import ColWiseParallel, RowWiseParallel, parallelize

# projection-name heuristics mirroring the reference's plan detection
# (high_level_api.py matches q/k/v/gate/up → colwise, o/out/down → rowwise)
_COLWISE_PAT = re.compile(
    r"(^|\.)((q|k|v|qkv)_?proj|query|key|value|gate_proj|up_proj|fc1|w1|w3|"
    r"in_proj|wi)$")
_ROWWISE_PAT = re.compile(
    r"(^|\.)((o|out)_?proj|dense|gate_up_down|down_proj|fc2|w2|wo)$")
_EMBED_PAT = re.compile(r"(^|\.)(embed\w*|wte|word_embeddings?)$")


def to_distributed(model, optimizer=None, mesh=None, config=None):
    """Inspect `model`, build a TP+FSDP plan from layer names/shapes, apply it.

    config keys (all optional): {"mp_axis": str, "dp_axis": str,
    "sharding_level": int (default 3 when a dp axis exists)}.
    Returns (model, optimizer, plan_dict)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("to_distributed needs a mesh "
                         "(or dist.auto_parallel.set_mesh)")
    jmesh = mesh.jax_mesh() if isinstance(mesh, ProcessMesh) else mesh
    names = list(jmesh.axis_names)
    config = dict(config or {})
    mp_axis = config.get("mp_axis", "mp" if "mp" in names else names[-1])
    dp_axis = config.get("dp_axis", "dp" if "dp" in names else names[0])
    sizes = dict(zip(jmesh.axis_names, jmesh.devices.shape))
    nmp = sizes.get(mp_axis, 1)

    plan = {}
    for lname, layer in model.named_sublayers(include_self=False):
        w = getattr(layer, "weight", None)
        if w is None or w.ndim != 2:
            continue
        if _EMBED_PAT.search(lname) and w.shape[0] % nmp == 0:
            plan[lname] = ColWiseParallel()       # vocab-dim shard
        elif _COLWISE_PAT.search(lname) and w.shape[1] % nmp == 0:
            plan[lname] = ColWiseParallel()
        elif _ROWWISE_PAT.search(lname) and w.shape[0] % nmp == 0:
            plan[lname] = RowWiseParallel()

    level = int(config.get("sharding_level",
                           3 if sizes.get(dp_axis, 1) > 1 else 0))
    model, optimizer = parallelize(
        model, optimizer, mesh,
        {"mp_config": {"parallelize_plan": plan} if plan else None,
         "dp_config": {"sharding_level": level}})
    model._dist_plan = {"tp": {k: type(v).__name__ for k, v in plan.items()},
                        "mp_axis": mp_axis, "dp_axis": dp_axis,
                        "sharding_level": level}
    return model, optimizer, model._dist_plan
