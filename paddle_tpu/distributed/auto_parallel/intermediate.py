"""One-call parallelization API (reference: python/paddle/distributed/
auto_parallel/intermediate/parallelize.py:51 `parallelize`, plus the plan
classes in intermediate/tensor_parallel.py — ColWiseParallel/RowWiseParallel/
SequenceParallel* — and intermediate/parallel_base.py).

TPU-native realization: a "plan" does not swap layer classes the way the
reference wraps sublayers; it assigns each matched parameter a NamedSharding
placement on the global mesh and (optionally) registers input/output
sharding-constraint hooks. GSPMD propagates everything else — the reference's
per-op dist branch collapses into the compiler.

Config schema (mirrors the reference's parallelize kwargs):

    parallelize(model, optimizer=None, mesh=None, config={
        "dp_config": {"sharding_level": 0|1|2|3},       # FSDP over 'dp' axis
        "mp_config": {"parallelize_plan": {
            "llama.embed_tokens":  ColWiseParallel(),    # fnmatch patterns
            "llama.layers.*.self_attn.q_proj": ColWiseParallel(),
            "llama.layers.*.self_attn.o_proj": RowWiseParallel(),
            ...
        }},
        "pp_config": {"split_spec": "llama.layers", "global_spec": ...},
    })
"""
from __future__ import annotations

import fnmatch

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core.tensor import Tensor
from .api import ProcessMesh, get_mesh


class PlanBase:
    """A parameter-placement rule applied to every layer matching a pattern."""

    def apply(self, layer, mesh, mp_axis):
        raise NotImplementedError


def _put(p, jmesh, spec):
    """Shard param p with `spec`, replicating any dim that doesn't divide."""
    if p is None:
        return
    sizes = dict(zip(jmesh.axis_names, jmesh.devices.shape))
    entries = list(spec) + [None] * (p.ndim - len(tuple(spec)))
    for d, e in enumerate(entries):
        if e is None:
            continue
        axes = e if isinstance(e, tuple) else (e,)
        n = int(np.prod([sizes[a] for a in axes]))
        if p._buf.shape[d] % n != 0:
            entries[d] = None
    p._data = jax.device_put(p._buf, NamedSharding(jmesh, P(*entries)))


def _constrain_to(jmesh, x, spec: P):
    """Sharding-constraint an activation on THIS mesh (unlike
    mp_layers._constrain, which binds to the global mp mesh). Tuples (layers
    returning (hidden, aux...)) constrain each float-Tensor member."""
    from ...core.dispatch import apply_op
    if isinstance(x, (tuple, list)):
        return type(x)(
            _constrain_to(jmesh, t, spec) if isinstance(t, Tensor) else t
            for t in x)
    sizes = dict(zip(jmesh.axis_names, jmesh.devices.shape))
    entries = list(spec) + [None] * (x.ndim - len(tuple(spec)))
    for d, e in enumerate(entries):
        if e is None:
            continue
        axes = e if isinstance(e, tuple) else (e,)
        n = int(np.prod([sizes[a] for a in axes]))
        if x.shape[d] % n != 0:
            entries[d] = None
    return apply_op("sharding_constraint",
                    lambda a: jax.lax.with_sharding_constraint(
                        a, NamedSharding(jmesh, P(*entries))), x)


class ColWiseParallel(PlanBase):
    """Megatron column parallel: Linear weight [in, out] shards the out dim on
    mp; bias shards too. Embedding weight [vocab, h] shards the vocab dim
    (reference intermediate/tensor_parallel.py ColWiseParallel, which handles
    both Linear and Embedding)."""

    def __init__(self, gather_output=False):
        self.gather_output = gather_output

    def apply(self, layer, jmesh, mp_axis):
        w = getattr(layer, "weight", None)
        if w is None:
            return
        if w.ndim == 2 and type(layer).__name__.lower().startswith("embed"):
            _put(w, jmesh, P(mp_axis, None))
        elif w.ndim == 2:
            _put(w, jmesh, P(None, mp_axis))
            _put(getattr(layer, "bias", None), jmesh, P(mp_axis))
        if self.gather_output:
            layer.register_forward_post_hook(
                lambda l, inp, out: _constrain_to(jmesh, out, P()))


class RowWiseParallel(PlanBase):
    """Megatron row parallel: weight [in, out] shards the in dim on mp; bias
    replicated (the partial-sum allreduce is GSPMD's job).

    is_input_parallel is accepted for reference API compatibility only: the
    reference uses it to decide whether to insert an input scatter, which
    GSPMD derives from the actual input sharding here — the knob has no
    effect."""

    def __init__(self, is_input_parallel=True):
        self.is_input_parallel = is_input_parallel

    def apply(self, layer, jmesh, mp_axis):
        w = getattr(layer, "weight", None)
        if w is not None and w.ndim == 2:
            _put(w, jmesh, P(mp_axis, None))


class SequenceParallelBegin(PlanBase):
    """Constrain the matched layer's OUTPUT to be sequence-sharded on mp —
    entering the SP region (reference SequenceParallelBegin)."""

    def apply(self, layer, jmesh, mp_axis):
        layer.register_forward_post_hook(
            lambda l, inp, out: _constrain_to(jmesh, out, P(None, mp_axis)))


class SequenceParallelEnd(PlanBase):
    """Constrain the matched layer's INPUT back to replicated-sequence —
    leaving the SP region (reference SequenceParallelEnd)."""

    def apply(self, layer, jmesh, mp_axis):
        layer.register_forward_pre_hook(
            lambda l, inp: tuple(
                _constrain_to(jmesh, t, P()) if isinstance(t, Tensor) else t
                for t in inp))


class SequenceParallelEnable(PlanBase):
    """Run the matched layer fully under sequence sharding (reference
    SequenceParallelEnable = Begin+End around one layer)."""

    def apply(self, layer, jmesh, mp_axis):
        SequenceParallelBegin().apply(layer, jmesh, mp_axis)
        SequenceParallelEnd().apply(layer, jmesh, mp_axis)


class PrepareLayerInput(PlanBase):
    """Apply a user fn to the matched layer's inputs (reference
    PrepareLayerInput)."""

    def __init__(self, fn):
        self.fn = fn

    def apply(self, layer, jmesh, mp_axis):
        layer.register_forward_pre_hook(self.fn)


class PrepareLayerOutput(PlanBase):
    """Apply a user fn to the matched layer's outputs (reference
    PrepareLayerOutput)."""

    def __init__(self, fn):
        self.fn = fn

    def apply(self, layer, jmesh, mp_axis):
        layer.register_forward_post_hook(self.fn)


def _apply_mp_plan(model, plan: dict, jmesh, mp_axis):
    named = dict(model.named_sublayers(include_self=True))
    matched = set()
    for pattern, rule in plan.items():
        rules = rule if isinstance(rule, (list, tuple)) else [rule]
        hits = [n for n in named if fnmatch.fnmatch(n, pattern)] or \
               [n for n in named if fnmatch.fnmatch(n, pattern + "*")]
        for n in (h for h in hits if h not in matched):
            for r in rules:
                r.apply(named[n], jmesh, mp_axis)
            matched.add(n)
    return matched


def _apply_fsdp(model, jmesh, dp_axis, level):
    """sharding_level 3: shard every parameter's largest free divisible dim on
    the dp axis — the GSPMD realization of ZeRO-3 param sharding. TP-sharded
    params keep their mp placement and gain dp on a free dim (the reference's
    sharding+TP composition; cf. models.llama.shard_llama P(dp, mp)). Levels
    1/2 differ only in what the OPTIMIZER shards, which paddle_tpu handles via
    accumulator sharding inheritance."""
    if level < 3:
        return   # grads/opt-state sharding rides on param/accumulator shardings
    ndp = dict(zip(jmesh.axis_names, jmesh.devices.shape)).get(dp_axis, 1)
    if ndp <= 1:
        return
    for _, p in model.named_parameters():
        if p.ndim == 0:
            continue
        sharding = getattr(p._buf, "sharding", None)
        spec = list(getattr(sharding, "spec", ()) or ())
        spec += [None] * (p.ndim - len(spec))
        if dp_axis in [a for e in spec if e is not None
                       for a in (e if isinstance(e, tuple) else (e,))]:
            continue          # already sharded on dp
        # TP-sharded params keep their mp placement; FSDP rides a free dim
        dims = sorted((d for d in range(p.ndim) if spec[d] is None),
                      key=lambda d: -p._buf.shape[d])
        for d in dims:
            if p._buf.shape[d] % ndp == 0:
                spec[d] = dp_axis
                break
        else:
            continue          # no divisible free dim — leave as-is
        p._data = jax.device_put(p._buf, NamedSharding(jmesh, P(*spec)))


def parallelize(model, optimizer=None, mesh=None, config=None):
    """One-call hybrid parallelization (reference parallelize.py:51).

    Returns (model, optimizer) — the same objects with parameters re-placed
    onto the mesh and sharding-constraint hooks installed. The pp_config
    split_spec is honored by constructing a PipelineLayer-compatible chunk
    boundary list stored on the model (consumed by fleet.distributed_model)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise ValueError("parallelize needs a mesh (or dist.auto_parallel.set_mesh)")
    jmesh = mesh.jax_mesh() if isinstance(mesh, ProcessMesh) else mesh
    config = config or {}
    names = list(jmesh.axis_names)
    mp_axis = "mp" if "mp" in names else names[-1]
    dp_axis = "dp" if "dp" in names else names[0]

    mp_cfg = config.get("mp_config") or {}
    if mp_cfg.get("parallelize_plan"):
        _apply_mp_plan(model, mp_cfg["parallelize_plan"], jmesh, mp_axis)

    dp_cfg = config.get("dp_config") or {}
    _apply_fsdp(model, jmesh, dp_axis, int(dp_cfg.get("sharding_level", 0)))

    pp_cfg = config.get("pp_config") or {}
    if pp_cfg.get("split_spec"):
        # recorded for downstream stage construction (PipelineLayer et al.);
        # automatic stage splitting from a name pattern is not applied here
        import warnings
        model._pp_split_spec = pp_cfg["split_spec"]
        warnings.warn(
            "parallelize(pp_config=...) records split_spec on the model but "
            "does not construct pipeline stages; build a PipelineLayer (e.g. "
            "LlamaForCausalLMPipe) and a PipelineParallel schedule for pp "
            "execution", stacklevel=2)

    return model, optimizer
