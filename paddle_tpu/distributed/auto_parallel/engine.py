"""Auto-parallel Strategy + Engine (reference: python/paddle/distributed/
auto_parallel/api.py:1886 `Strategy`; static/engine.py:99 `Engine`, fit:1533).

The reference Engine lowers the model to a static distributed program
(completion → partition → reshard passes) and drives it with an executor.
TPU-native: the "distributed program" is one jit-compiled XLA module — the
train step (forward + backward + optimizer update, with GSPMD shardings from
the parameters' NamedShardings) is captured via paddle_tpu.jit.to_static, and
the per-rank partitioning/reshard insertion is XLA's SPMD partitioner.
"""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor
from ...core.dispatch import unwrap
from .api import ProcessMesh, get_mesh, shard_tensor
from .placement import Shard, Replicate


class _Config:
    """Attribute-dict config node (mirrors the reference's Strategy sub-config
    objects, auto_parallel/strategy.py)."""

    def __init__(self, **defaults):
        self.__dict__.update(defaults)

    def __repr__(self):
        return f"_Config({self.__dict__})"


class Strategy:
    """reference auto_parallel/api.py:1886 — configuration bundle for
    parallelization choices. Fields mirror the reference's sub-configs:

      strategy.sharding.{enable, degree, stage}
      strategy.amp.{enable, dtype, level}
      strategy.recompute.{enable}
      strategy.pipeline.{enable, schedule_mode, micro_batch_size,
                         accumulate_steps}
      strategy.gradient_merge.{enable, k_steps}
      strategy.dataset.{micro_batch_size}
    """

    def __init__(self, config=None):
        config = config or {}

        def sub(key, **defaults):
            defaults.update(config.get(key, {}))
            return _Config(**defaults)

        self.sharding = sub("sharding", enable=False, degree=-1, stage=1)
        self.amp = sub("amp", enable=False, dtype="float16", level="O1")
        self.recompute = sub("recompute", enable=False)
        self.pipeline = sub("pipeline", enable=False, schedule_mode="1F1B",
                            micro_batch_size=1, accumulate_steps=1)
        self.gradient_merge = sub("gradient_merge", enable=False, k_steps=1)
        self.fused_passes = sub("fused_passes", enable=False,
                                fused_passes_list=[])
        self.dataset = sub("dataset", micro_batch_size=1)

    def __repr__(self):
        return (f"Strategy(sharding={self.sharding}, amp={self.amp}, "
                f"pipeline={self.pipeline})")


class Engine:
    """reference static/engine.py:99. fit/evaluate/predict drive a compiled
    train/eval/predict step; `to_static=False` mode (dygraph fallback) runs the
    same step eagerly — useful when Python control flow graph-breaks."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None, mesh=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = metrics if isinstance(metrics, (list, tuple)) else \
            ([metrics] if metrics else [])
        self._strategy = strategy or Strategy()
        mesh = mesh or get_mesh()
        if mesh is not None and not isinstance(mesh, ProcessMesh):
            # accept a raw jax.sharding.Mesh like parallelize/to_distributed
            # do, preserving the caller's device order
            mesh = ProcessMesh.from_jax_mesh(mesh)
        self._mesh = mesh
        self._compiled = {}         # mode -> compiled step
        self.history = {"loss": []}

    # ---- data placement ------------------------------------------------------
    def _dp_axis(self):
        if self._mesh is None:
            return None
        names = self._mesh.dim_names
        for cand in ("dp", "data", "x"):
            if cand in names:
                return cand
        return names[0]

    def _place_batch(self, t):
        """Shard the batch dim over the dp axis; replicate elsewhere (the
        reference's dist dataloader does the same split per rank)."""
        if self._mesh is None:
            return t if isinstance(t, Tensor) else Tensor(np.asarray(t))
        t = t if isinstance(t, Tensor) else Tensor(np.asarray(t))
        axis = self._dp_axis()
        nd = self._mesh.get_dim_size(axis)
        if t.ndim == 0 or t.shape[0] % nd != 0:
            placements = [Replicate() for _ in self._mesh.dim_names]
        else:
            placements = [Shard(0) if n == axis else Replicate()
                          for n in self._mesh.dim_names]
        return shard_tensor(t, self._mesh, placements,
                            stop_gradient=t.stop_gradient)

    # ---- steps ---------------------------------------------------------------
    def _train_step(self, x, y):
        self._model.train()
        out = self._model(x)
        loss = self._loss(out, y) if self._loss is not None else out
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        return loss

    def _get_step(self, mode):
        if mode in self._compiled:
            return self._compiled[mode]
        if mode == "train":
            from ...jit import to_static
            step = to_static(self._train_step)
        elif mode == "eval":
            def step(x, y):
                self._model.eval()
                out = self._model(x)
                return self._loss(out, y) if self._loss is not None else out
        else:
            def step(x):
                self._model.eval()
                return self._model(x)
        self._compiled[mode] = step
        return step

    @staticmethod
    def _iter_batches(data, batch_size, steps=None):
        from ...io import DataLoader
        if isinstance(data, DataLoader):
            it = iter(data)
        elif isinstance(data, (tuple, list)) and len(data) == 2 and \
                hasattr(data[0], "shape"):
            xs, ys = np.asarray(data[0]), np.asarray(data[1])

            def gen():   # tail remainder included (partial batch replicates)
                for i in range(0, len(xs), batch_size):
                    yield xs[i:i + batch_size], ys[i:i + batch_size]
            it = gen()
        else:
            it = iter(DataLoader(data, batch_size=batch_size))
        for k, batch in enumerate(it):
            if steps is not None and k >= steps:
                return
            yield batch

    # ---- public API ----------------------------------------------------------
    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Warm the compile cache for `mode` from specs (reference
        Engine.prepare builds the static program up front). Side-effect-free:
        the capture run's mutations to model weights and optimizer state are
        rolled back (jax arrays are immutable, so the snapshot is refs)."""
        if inputs_spec is None:
            return self
        saved_model = {k: v._data for k, v in self._model.state_dict().items()}
        saved_acc = None
        opt = self._optimizer
        if mode == "train" and opt is not None:
            # snapshot BOTH values and key-sets: accumulators are created
            # lazily inside step(), so anything new after the warm-up run is
            # synthetic-state and must be dropped, not just restored
            saved_acc = {name: {pid: t._data for pid, t in store.items()}
                         for name, store in opt._accumulators.items()}
            saved_step = opt._global_step._data
        x = Tensor(np.zeros(inputs_spec.shape, dtype=inputs_spec.dtype))
        try:
            if mode == "predict":
                self._get_step(mode)(self._place_batch(x))
            elif labels_spec is not None:
                y = Tensor(np.zeros(labels_spec.shape, dtype=labels_spec.dtype))
                self._get_step(mode)(self._place_batch(x), self._place_batch(y))
        finally:
            sd = self._model.state_dict()
            for k, arr in saved_model.items():
                if k in sd:
                    sd[k]._data = arr
            if saved_acc is not None:
                opt._global_step._data = saved_step
                for name in list(opt._accumulators):
                    if name not in saved_acc:
                        del opt._accumulators[name]   # lazily created: drop
                        continue
                    store, saved = opt._accumulators[name], saved_acc[name]
                    for pid in list(store):
                        if pid in saved:
                            store[pid]._data = saved[pid]
                        else:
                            del store[pid]
        return self

    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            valid_data=None, log_freq=0, verbose=0):
        step = self._get_step("train")
        for epoch in range(epochs):
            for k, (bx, by) in enumerate(
                    self._iter_batches(train_data, batch_size, steps_per_epoch)):
                loss = step(self._place_batch(bx), self._place_batch(by))
                lv = float(unwrap(loss.detach() if hasattr(loss, "detach")
                                  else loss).mean())
                self.history["loss"].append(lv)
                if log_freq and k % log_freq == 0 and verbose:
                    print(f"[Engine] epoch {epoch} step {k}: "  # graftlint: disable=no-adhoc-telemetry
                          f"loss={lv:.5f}")
            if valid_data is not None:
                self.evaluate(valid_data, batch_size=batch_size)
        return self.history

    def evaluate(self, valid_data, batch_size=1, steps=None):
        step = self._get_step("eval")
        losses = []
        for bx, by in self._iter_batches(valid_data, batch_size, steps):
            loss = step(self._place_batch(bx), self._place_batch(by))
            losses.append(float(unwrap(loss).mean()))
        out = {"loss": float(np.mean(losses)) if losses else float("nan")}
        self.history.setdefault("eval_loss", []).append(out["loss"])
        return out

    def predict(self, test_data, batch_size=1, steps=None):
        step = self._get_step("predict")
        outs = []
        for batch in self._iter_batches(
                test_data if not (isinstance(test_data, (tuple, list)) and
                                  len(test_data) == 2)
                else (test_data[0], test_data[0]), batch_size, steps):
            bx = batch[0] if isinstance(batch, (tuple, list)) else batch
            out = step(self._place_batch(bx))
            outs.append(np.asarray(unwrap(out)))
        return outs

    def save(self, path, training=True):
        from ...framework.io import save
        state = {"model": self._model.state_dict()}
        if training and self._optimizer is not None:
            state["optimizer"] = self._optimizer.state_dict()
        save(state, path)

    def load(self, path):
        from ...framework.io import load
        state = load(path)
        self._model.set_state_dict(state["model"])
        if self._optimizer is not None and "optimizer" in state:
            self._optimizer.set_state_dict(state["optimizer"])
        return self
