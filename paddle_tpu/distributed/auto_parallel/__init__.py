from .api import (ProcessMesh, shard_tensor, reshard, shard_layer, set_mesh,  # noqa: F401
                  get_mesh, dtensor_from_fn, unshard_dtensor, shard_optimizer,
                  local_map, get_placements, get_process_mesh)
from .placement import Placement, Replicate, Shard, Partial  # noqa: F401
from .intermediate import (parallelize, ColWiseParallel, RowWiseParallel,  # noqa: F401
                           SequenceParallelBegin, SequenceParallelEnd,
                           SequenceParallelEnable, PrepareLayerInput,
                           PrepareLayerOutput)
from .engine import Engine, Strategy  # noqa: F401
from .high_level_api import to_distributed  # noqa: F401
