from .api import (ProcessMesh, shard_tensor, reshard, shard_layer, set_mesh,  # noqa: F401
                  get_mesh, dtensor_from_fn, unshard_dtensor, shard_optimizer,
                  local_map, get_placements, get_process_mesh)
from .placement import Placement, Replicate, Shard, Partial  # noqa: F401
