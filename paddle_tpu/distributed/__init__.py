"""paddle.distributed surface (reference: python/paddle/distributed/__init__.py).

TPU-native design (SURVEY §7): one ND device mesh + GSPMD shardings replace
process groups; explicit collectives run via shard_map; rendezvous via JAX's
coordination service.
"""
from .env import (ParallelEnv, get_rank, get_world_size, init_parallel_env,  # noqa: F401
                  is_initialized)
from .parallel import DataParallel  # noqa: F401

# filled in as the distributed stack lands this round:
from .auto_parallel.api import (ProcessMesh, shard_tensor, reshard, shard_layer,  # noqa: F401
                                dtensor_from_fn, unshard_dtensor)
from .auto_parallel.placement import (Placement, Replicate, Shard, Partial)  # noqa: F401
from .auto_parallel import (parallelize, to_distributed, Engine, Strategy,  # noqa: F401
                            ColWiseParallel, RowWiseParallel,
                            SequenceParallelBegin, SequenceParallelEnd,
                            SequenceParallelEnable)
from .watchdog import CommTaskManager  # noqa: F401
from .collective import (all_reduce, all_gather, all_gather_object, reduce,  # noqa: F401
                         broadcast, scatter, all_to_all, reduce_scatter,
                         send, recv, barrier, new_group, get_group, ReduceOp,
                         split_group, broadcast_object_list, alltoall,
                         all_to_all_single, gather, gather_object,
                         scatter_object_list, isend, irecv, wait, P2POp,
                         batch_isend_irecv, destroy_process_group)
from . import mesh_utils  # noqa: F401
from .mesh_utils import create_mesh, create_hybrid_mesh  # noqa: F401
from . import fleet  # noqa: F401
from . import checkpoint  # noqa: F401
from . import ps_sparse  # noqa: F401  (host-resident sparse embedding PS)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference: distributed/spawn.py:463 — single-node multiprocess launch.
    Children can call init_parallel_env(): a coordinator address on a free
    port is provisioned here."""
    import multiprocessing as mp
    import socket
    if nprocs == -1:
        import jax
        nprocs = jax.device_count()
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    ctx = mp.get_context("spawn")
    # Children of a CPU-bound parent must stay CPU-bound.  The accelerator
    # plugin registers from sitecustomize at interpreter STARTUP — before any
    # code we pass to the child runs — so the discovery vars must be scrubbed
    # from the parent's environ while the children launch (spawn-context
    # children snapshot os.environ at start()).
    import os
    from paddle_tpu.core.hermetic import scrub_plugin_vars
    cpu_parent = os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
    removed = scrub_plugin_vars() if cpu_parent else {}
    procs = []
    try:
        for rank in range(nprocs):
            env = {"PADDLE_TRAINER_ID": str(rank),
                   "PADDLE_TRAINERS_NUM": str(nprocs),
                   "PADDLE_LOCAL_RANK": str(rank),
                   "PADDLE_MASTER": f"127.0.0.1:{port}",
                   "MASTER_ADDR": "127.0.0.1", "MASTER_PORT": str(port)}
            p = ctx.Process(target=_spawn_entry, args=(func, args, env),
                            daemon=daemon)
            p.start()
            procs.append(p)
    finally:
        os.environ.update(removed)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode != 0]
        if bad:
            raise RuntimeError(f"spawn: worker(s) failed with exit codes {bad}")
    return procs


def _spawn_entry(func, args, env):
    import os
    os.environ.update(env)
    func(*args)
