"""Device-mesh construction helpers (SURVEY §5 comm-backend note: the TPU
control plane needs DCN-aware mesh construction for multi-slice jobs —
reference equivalent: fleet topology ordering ranks so NCCL rings stay
intra-node, topology.py:199).

`create_mesh` builds a jax Mesh whose FAST axes ride ICI (within a slice)
and whose slow axes span DCN (across slices/hosts), using
jax.experimental.mesh_utils so device order respects the physical torus."""
from __future__ import annotations

import numpy as np
import jax

__all__ = ["create_mesh", "create_hybrid_mesh"]


def create_mesh(axis_shapes, axis_names=None, devices=None):
    """Single-slice mesh: axis_shapes like {'dp': 2, 'mp': 4} or a tuple.
    Uses mesh_utils.create_device_mesh so the axis order maps onto the ICI
    torus instead of raw device enumeration."""
    if isinstance(axis_shapes, dict):
        names = list(axis_shapes)
        shape = [axis_shapes[n] for n in names]
    else:
        shape = list(axis_shapes)
        names = list(axis_names or [f"d{i}" for i in range(len(shape))])
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(tuple(shape),
                                                  devices=devices[:n])
    except Exception:   # non-TPU backends: plain reshape is fine
        dev_array = np.array(devices[:n]).reshape(shape)
    return jax.sharding.Mesh(dev_array, tuple(names))


def create_hybrid_mesh(ici_axis_shapes, dcn_axis_shapes, devices=None):
    """Multi-slice mesh with PER-AXIS (ICI x DCN) factors — the maxtext-style
    contract of jax's create_hybrid_device_mesh: both dicts share the same
    axis names, axis i's final size is ici_i * dcn_i, and the helper places
    the DCN factor major so collectives along an axis whose dcn factor is 1
    never cross the data-center network. Put dp/pp's growth in dcn factors
    and keep mp/sep at dcn=1 (the scaling-book recipe).

        create_hybrid_mesh({"dp": 2, "mp": 4}, {"dp": 2, "mp": 1})
        -> Mesh [dp=4, mp=4] over 2 slices of 8 chips
    """
    if isinstance(ici_axis_shapes, dict):
        names = list(ici_axis_shapes)
        ici = [int(ici_axis_shapes[n]) for n in names]
        if not isinstance(dcn_axis_shapes, dict):
            raise ValueError("pass both shapes as dicts with the same keys")
        dcn = [int(dcn_axis_shapes.get(n, 1)) for n in names]
    else:
        ici = [int(v) for v in ici_axis_shapes]
        dcn = [int(v) for v in dcn_axis_shapes]
        if len(ici) != len(dcn):
            raise ValueError("ici and dcn factor lists must align per axis")
        names = [f"d{i}" for i in range(len(ici))]
    devices = list(devices if devices is not None else jax.devices())
    final = tuple(i * d for i, d in zip(ici, dcn))
    n = int(np.prod(final))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_hybrid_device_mesh(
            tuple(ici), tuple(dcn), devices=devices[:n],
            allow_split_physical_axes=True)
    except Exception:
        # no slice topology info (CPU/sim): emulate dcn-major placement so
        # each axis is [dcn factor major, ici factor minor] over enumeration
        # order (devices of one "slice" stay contiguous on the ici factors)
        arr = np.array(devices[:n]).reshape(tuple(dcn) + tuple(ici))
        k = len(ici)
        perm = [x for i in range(k) for x in (i, k + i)]
        dev_array = arr.transpose(perm).reshape(final)
    return jax.sharding.Mesh(dev_array, tuple(names))
