"""Device-mesh construction helpers (SURVEY §5 comm-backend note: the TPU
control plane needs DCN-aware mesh construction for multi-slice jobs —
reference equivalent: fleet topology ordering ranks so NCCL rings stay
intra-node, topology.py:199).

`create_mesh` builds a jax Mesh whose FAST axes ride ICI (within a slice)
and whose slow axes span DCN (across slices/hosts), using
jax.experimental.mesh_utils so device order respects the physical torus."""
from __future__ import annotations

import numpy as np
import jax

__all__ = ["create_mesh", "create_hybrid_mesh"]


def create_mesh(axis_shapes, axis_names=None, devices=None):
    """Single-slice mesh: axis_shapes like {'dp': 2, 'mp': 4} or a tuple.
    Uses mesh_utils.create_device_mesh so the axis order maps onto the ICI
    torus instead of raw device enumeration."""
    if isinstance(axis_shapes, dict):
        names = list(axis_shapes)
        shape = [axis_shapes[n] for n in names]
    else:
        shape = list(axis_shapes)
        names = list(axis_names or [f"d{i}" for i in range(len(shape))])
    devices = list(devices if devices is not None else jax.devices())
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(tuple(shape),
                                                  devices=devices[:n])
    except Exception:   # non-TPU backends: plain reshape is fine
        dev_array = np.array(devices[:n]).reshape(shape)
    return jax.sharding.Mesh(dev_array, tuple(names))


def create_hybrid_mesh(dcn_axis_shapes, ici_axis_shapes, axis_names=None,
                       devices=None):
    """Multi-slice mesh: leading axes span DCN (one entry per slice), the
    rest ride ICI inside each slice. Put dp/pp on the DCN axes and mp/sep on
    ICI — collectives on the fast axes then never cross the data-center
    network (the scaling-book mesh recipe; reference ranks order dp slowest
    for the same reason)."""
    dcn = list(dcn_axis_shapes.values()) if isinstance(dcn_axis_shapes, dict) \
        else list(dcn_axis_shapes)
    ici = list(ici_axis_shapes.values()) if isinstance(ici_axis_shapes, dict) \
        else list(ici_axis_shapes)
    if axis_names is None:
        dn = list(dcn_axis_shapes) if isinstance(dcn_axis_shapes, dict) else \
            [f"dcn{i}" for i in range(len(dcn))]
        im = list(ici_axis_shapes) if isinstance(ici_axis_shapes, dict) else \
            [f"ici{i}" for i in range(len(ici))]
        axis_names = dn + im
    devices = list(devices if devices is not None else jax.devices())
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_hybrid_device_mesh(
            tuple(ici), tuple(dcn), devices=devices,
            allow_split_physical_axes=True)
        # hybrid helper returns [dcn..., ici...]-shaped array
        dev_array = dev_array.reshape(tuple(dcn) + tuple(ici))
    except Exception:
        n = int(np.prod(dcn + ici))
        if n > len(devices):
            raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
        dev_array = np.array(devices[:n]).reshape(tuple(dcn) + tuple(ici))
    return jax.sharding.Mesh(dev_array, tuple(axis_names))
