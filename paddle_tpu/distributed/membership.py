"""Lease-based membership over :class:`~paddle_tpu.distributed.store.TCPStore`.

etcd-style membership for the serving fleet, built from the store's own
primitives instead of a new service: a member ``register()``s under a TTL
lease and an ADD-derived **epoch** (monotonic across restarts of the same
name — a respawned worker is a *new* incarnation, never confused with its
dead predecessor), a heartbeat thread renews the lease through
:class:`~paddle_tpu.core.retry.RetryPolicy`, and any number of watchers
diff the membership view into typed ``join`` / ``leave`` / ``expire``
events.

Store layout (all under ``ms/<group>/``)::

    ms/<group>/index          pickled sorted list of member names; every
                              mutation is a raw-bytes compare_and_set loop,
                              so concurrent joins/leaves never lose updates
    ms/<group>/epoch/<name>   ADD counter — the epoch source
    ms/<group>/m/<name>       pickled member record {name, epoch, meta,
                              expires_at}

Clocks: ``expires_at`` is an absolute reading of the injectable ``clock``
(default ``time.monotonic`` — CLOCK_MONOTONIC, shared by every process on
one host).  Tests inject one fake clock into the service on both sides and
drive expiry by advancing it; multi-host deployments must supply a
host-comparable clock (e.g. ``time.time`` under NTP).

Failure semantics: a member that stops renewing (crash, wedge, kill -9)
keeps its record in the store until a watcher's :meth:`MembershipWatcher.poll`
observes ``expires_at`` in the past — the watcher then emits ``expire``,
reaps the record, and bumps ``membership_lease_expiries_total``.  A clean
:meth:`Lease.release` deletes the record immediately (``leave``); the store's
typed deleted-miss keeps concurrent readers from stalling on the vanished
key.

Fault points (:mod:`paddle_tpu.testing.faults`): ``membership.register``
fires inside registration, ``membership.heartbeat`` inside every renewal
attempt — chaos tests starve a lease to death with ``Always`` or exercise
the retry path with ``FailNth``.
"""
from __future__ import annotations

import pickle
import threading
import time

from .. import observability as _obs
from ..core.retry import RetryError, RetryPolicy, retry_call
from ..testing.faults import FAULTS as _faults
from ..testing.faults import InjectedFault as _InjectedFault
from .store import StoreKeyDeleted

__all__ = ["MemberInfo", "MembershipEvent", "Lease", "LeaseLostError",
           "MembershipService", "MembershipWatcher",
           "JOIN", "LEAVE", "EXPIRE"]

JOIN, LEAVE, EXPIRE = "join", "leave", "expire"

# store errors any single membership op may transiently hit
_STORE_ERRORS = (OSError, ConnectionError, TimeoutError, _InjectedFault)


class LeaseLostError(RuntimeError):
    """The heartbeat could not renew the lease before it ran out of
    retries — the member must assume the fleet has expired it."""


class MemberInfo:
    """One member's registered state as read from the store."""

    __slots__ = ("name", "epoch", "meta", "expires_at")

    def __init__(self, name, epoch, meta, expires_at):
        self.name = name
        self.epoch = int(epoch)
        self.meta = meta
        self.expires_at = float(expires_at)

    def __repr__(self):
        return (f"MemberInfo({self.name!r}, epoch={self.epoch}, "
                f"expires_at={self.expires_at:.3f})")


class MembershipEvent:
    """One typed membership transition: ``kind`` is ``join`` (new name or
    new epoch of a known name), ``leave`` (record cleanly gone), or
    ``expire`` (lease TTL lapsed without renewal)."""

    __slots__ = ("kind", "member")

    def __init__(self, kind, member):
        self.kind = kind
        self.member = member

    def __repr__(self):
        return f"MembershipEvent({self.kind}, {self.member!r})"


class MembershipService:
    """Shared view of one membership group over one store client.

    Thread-safe for the operations one process performs (register + its
    lease heartbeats + watcher polls): the store client serializes on its
    own socket lock and index mutations are CAS loops.
    """

    def __init__(self, store, group="fleet", ttl=2.0, clock=time.monotonic,
                 retry_policy=None):
        if float(ttl) <= 0:
            raise ValueError("ttl must be > 0")
        self.store = store
        self.group = str(group)
        self.ttl = float(ttl)
        self.clock = clock
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=5, base_delay=0.02, max_delay=0.25)

    # ---- key layout ----------------------------------------------------------
    def _k_index(self):
        return f"ms/{self.group}/index"

    def _k_epoch(self, name):
        return f"ms/{self.group}/epoch/{name}"

    def _k_member(self, name):
        return f"ms/{self.group}/m/{name}"

    # ---- registration / records ----------------------------------------------
    def register(self, name, meta=None):
        """Join the group: allocate the next epoch for ``name``, write the
        lease record, and add the name to the index.  Returns the
        :class:`Lease` whose heartbeat keeps the membership alive."""
        name = str(name)
        _faults.maybe_fire("membership.register", group=self.group,
                           member=name)
        epoch = int(self.store.add(self._k_epoch(name), 1))
        expires_at = self._write_record(name, epoch, meta)
        self._index_update(lambda names: names | {name})
        return Lease(self, name, epoch, meta, expires_at)

    def _write_record(self, name, epoch, meta):
        expires_at = float(self.clock()) + self.ttl
        self.store.set(self._k_member(name), {
            "name": name, "epoch": epoch, "meta": meta,
            "expires_at": expires_at})
        return expires_at

    def _remove_member(self, name):
        """Best-effort reap of one member's record + index entry (release
        and watcher-expiry share this)."""
        try:
            self.store.delete_key(self._k_member(name))
        finally:
            self._index_update(lambda names: names - {name})

    def evict(self, name):
        """Administratively remove ``name`` from the group NOW — watchers
        observe ``leave`` on their next poll instead of waiting out the TTL.
        This is the third-party counterpart of :meth:`Lease.release` for
        members that cannot release themselves: the supervisor evicts a
        quarantined crash-looper so routers stop selecting it immediately.
        Idempotent; a concurrent release/expiry of the same name is
        harmless (both paths reap the same record)."""
        self._remove_member(str(name))

    def _index_update(self, mutate):
        """Raw-bytes CAS loop over the index key — lost updates are
        impossible, concurrent mutators just retry on the fresh bytes."""
        while True:
            try:
                raw = self.store.get_raw(self._k_index(), timeout=0.05)
            except (TimeoutError, StoreKeyDeleted):
                raw = None
            names = set(pickle.loads(raw)) if raw else set()
            new = mutate(set(names))
            if new == names:
                return
            swapped, _ = self.store.compare_and_set(
                self._k_index(), raw, sorted(new))
            if swapped:
                return

    # ---- read side -----------------------------------------------------------
    def members(self):
        """Every member with a readable record, keyed by name — including
        ones already past expiry (the watcher decides their fate).  A name
        in the index whose record is gone (release in flight, or a crashed
        pre-record registration) is skipped."""
        try:
            names = self.store.get(self._k_index(), timeout=0.05)
        except (TimeoutError, StoreKeyDeleted):
            return {}
        out = {}
        for name in names:
            try:
                rec = self.store.get(self._k_member(name), timeout=0.05)
            except (TimeoutError, StoreKeyDeleted):
                continue
            out[name] = MemberInfo(rec["name"], rec["epoch"], rec["meta"],
                                   rec["expires_at"])
        return out

    def watch(self):
        """A fresh :class:`MembershipWatcher` over this group (its first
        :meth:`~MembershipWatcher.poll` reports every live member as a
        ``join``)."""
        return MembershipWatcher(self)


class Lease:
    """A member's live claim on its name: renew it, release it, or let the
    heartbeat thread do the renewing until :meth:`stop_heartbeat`."""

    def __init__(self, service, name, epoch, meta, expires_at):
        self.service = service
        self.name = name
        self.epoch = int(epoch)
        self.meta = meta
        self.expires_at = float(expires_at)
        self.lost = False
        self.released = False
        self._hb_stop = threading.Event()
        self._hb_thread = None
        self._on_lost = None

    # ---- renewal -------------------------------------------------------------
    def renew(self):
        """One lease renewal through the service's retry policy; raises
        :class:`LeaseLostError` when every attempt fails.  Latency lands in
        ``membership_heartbeat_seconds``."""
        svc = self.service
        t0 = time.perf_counter()

        def attempt():
            _faults.maybe_fire("membership.heartbeat", group=svc.group,
                               member=self.name)
            return svc._write_record(self.name, self.epoch, self.meta)

        try:
            self.expires_at = retry_call(
                attempt, policy=svc.retry_policy, retry_on=_STORE_ERRORS,
                op="membership.heartbeat")
        except RetryError as e:
            self.lost = True
            raise LeaseLostError(
                f"lease {self.name!r} (epoch {self.epoch}) could not renew: "
                f"{e}") from e
        _obs.MEMBERSHIP_HEARTBEAT_SECONDS.observe(
            time.perf_counter() - t0, group=svc.group)
        return self.expires_at

    def start_heartbeat(self, interval=None, on_lost=None):
        """Renew every ``interval`` seconds (default ``ttl / 3``) from a
        named daemon thread until :meth:`stop_heartbeat` / :meth:`release`.
        A renewal that exhausts its retries marks the lease ``lost``, calls
        ``on_lost(error)`` once, and stops the thread — the owner decides
        whether to exit or re-register."""
        if self._hb_thread is not None:
            return self
        self._on_lost = on_lost
        self._hb_interval = (self.service.ttl / 3.0 if interval is None
                             else float(interval))
        self._hb_stop.clear()
        self._hb_thread = threading.Thread(
            target=self._hb_loop, name=f"lease-hb-{self.name}", daemon=True)
        self._hb_thread.start()
        return self

    def _hb_loop(self):
        while not self._hb_stop.wait(self._hb_interval):
            try:
                self.renew()
            except LeaseLostError as e:
                if self._on_lost is not None:
                    self._on_lost(e)
                return

    def stop_heartbeat(self):
        self._hb_stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=10.0)
            self._hb_thread = None

    # ---- teardown ------------------------------------------------------------
    def release(self):
        """Graceful leave: stop the heartbeat and delete the record so
        watchers see ``leave`` immediately (no TTL wait).  Idempotent."""
        self.stop_heartbeat()
        if self.released:
            return
        self.released = True
        self.service._remove_member(self.name)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.release()


class MembershipWatcher:
    """Diffs successive membership snapshots into typed events.

    :meth:`poll` is the deterministic unit tests and the fleet's sync loop
    call directly; :meth:`start` wraps it in a background thread for
    wall-clock deployments.  Expired members are REAPED by the watcher (the
    record and index entry are deleted) so one watcher cleaning up is
    enough and ``members()`` converges for everyone.
    """

    def __init__(self, service):
        self.service = service
        self._last = {}          # name -> MemberInfo of live members
        self._thread = None
        self._stop = threading.Event()

    def poll(self):
        """One membership diff; returns the (possibly empty) event list in
        deterministic name order: expires, then leaves, then joins."""
        svc = self.service
        now = float(svc.clock())
        current = svc.members()
        events = []
        live = {}
        for name in sorted(current):
            info = current[name]
            if info.expires_at <= now:
                prev = self._last.get(name)
                # an expired record we never saw alive still expires — the
                # member died before any watcher observed it
                events.append(MembershipEvent(EXPIRE, info))
                _obs.MEMBERSHIP_LEASE_EXPIRIES.inc(group=svc.group)
                svc._remove_member(name)
                if prev is not None and prev.epoch != info.epoch:
                    pass  # the newer epoch already superseded what we knew
            else:
                live[name] = info
        for name in sorted(self._last):
            if name not in current:
                events.append(MembershipEvent(LEAVE, self._last[name]))
        for name in sorted(live):
            prev = self._last.get(name)
            if prev is None or prev.epoch != live[name].epoch:
                events.append(MembershipEvent(JOIN, live[name]))
        self._last = live
        for ev in events:
            _obs.MEMBERSHIP_EVENTS.inc(group=svc.group, kind=ev.kind)
        return events

    def members(self):
        """The watcher's current view of live members (last poll)."""
        return dict(self._last)

    # ---- background loop -----------------------------------------------------
    def start(self, interval=0.5, on_event=None):
        """Poll every ``interval`` seconds from a daemon thread, feeding
        each event to ``on_event``; :meth:`stop` joins the thread."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    events = self.poll()
                except _STORE_ERRORS:
                    continue  # store hiccup: next tick retries the diff
                if on_event is not None:
                    for ev in events:
                        on_event(ev)

        self._thread = threading.Thread(
            target=loop, name=f"membership-watch-{self.service.group}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
