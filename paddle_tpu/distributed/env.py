"""Process-level distributed environment.

Reference: python/paddle/distributed/parallel.py (ParallelEnv, env vars
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM). On TPU, multi-host process bring-up is
jax.distributed.initialize; within a host, devices are addressable directly.
"""
from __future__ import annotations

import os

import jax


class ParallelEnv:
    def __init__(self):
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                       os.environ.get("RANK", "0")))
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                             os.environ.get("WORLD_SIZE", "1")))
        self.device_id = int(os.environ.get("FLAGS_selected_tpus",
                                            os.environ.get("LOCAL_RANK", "0")))
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self.trainer_endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank


_parallel_env = None
_initialized = False


def _env() -> ParallelEnv:
    global _parallel_env
    if _parallel_env is None:
        _parallel_env = ParallelEnv()
    return _parallel_env


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_rank()
    return _env().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return group.get_world_size()
    return _env().world_size


_store = None


def init_parallel_env():
    """reference: distributed/parallel.py:978 init_parallel_env.

    Multi-host: jax.distributed.initialize (rendezvous through JAX's
    coordination service) + a TCPStore on the master for the control plane
    (p2p payloads, barriers, user KV — reference tcp_store.h:121).
    Single-host multi-device needs no process bring-up on TPU.
    """
    global _initialized, _store
    env = _env()
    if _initialized:
        return env
    coord = os.environ.get("PADDLE_MASTER", os.environ.get("MASTER_ADDR"))
    if env.world_size > 1 and coord:
        host = coord.split(":")[0]
        port = int(os.environ.get("MASTER_PORT",
                                  coord.split(":")[1] if ":" in coord
                                  else "8476"))
        # importing paddle_tpu may already have touched the XLA backend;
        # drop it so the coordination service can come up first
        import jax.extend.backend
        jax.extend.backend.clear_backends()
        jax.distributed.initialize(coordinator_address=f"{host}:{port}",
                                   num_processes=env.world_size,
                                   process_id=env.rank)
        from .store import TCPStore
        # store rides master port + 1000 (worker endpoints use +1..+world)
        _store = TCPStore(host, port + 1000, is_master=(env.rank == 0),
                          world_size=env.world_size)
    _initialized = True
    return env


def get_store():
    """The job's control-plane TCPStore (None when single-process)."""
    return _store


def is_initialized() -> bool:
    return _initialized


def parallel_device_count() -> int:
    return jax.device_count()
