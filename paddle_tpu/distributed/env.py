"""Process-level distributed environment.

Reference: python/paddle/distributed/parallel.py (ParallelEnv, env vars
PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM). On TPU, multi-host process bring-up is
jax.distributed.initialize; within a host, devices are addressable directly.
"""
from __future__ import annotations

import os

import jax


class ParallelEnv:
    def __init__(self):
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID",
                                       os.environ.get("RANK", "0")))
        self.world_size = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                             os.environ.get("WORLD_SIZE", "1")))
        self.device_id = int(os.environ.get("FLAGS_selected_tpus",
                                            os.environ.get("LOCAL_RANK", "0")))
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        self.trainer_endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")

    @property
    def nranks(self):
        return self.world_size

    @property
    def local_rank(self):
        return self.rank


_parallel_env = None
_initialized = False


def _env() -> ParallelEnv:
    global _parallel_env
    if _parallel_env is None:
        _parallel_env = ParallelEnv()
    return _parallel_env


def get_rank(group=None) -> int:
    if group is not None:
        return group.get_rank()
    return _env().rank


def get_world_size(group=None) -> int:
    if group is not None:
        return group.get_world_size()
    return _env().world_size


def init_parallel_env():
    """reference: distributed/parallel.py:978 init_parallel_env.

    Multi-host: jax.distributed.initialize using the launcher-provided
    coordinator address (the TCPStore analog is JAX's coordination service).
    Single-host multi-device needs no process bring-up on TPU.
    """
    global _initialized
    env = _env()
    if _initialized:
        return env
    coord = os.environ.get("PADDLE_MASTER", os.environ.get("MASTER_ADDR"))
    if env.world_size > 1 and coord:
        port = os.environ.get("MASTER_PORT", "8476")
        addr = coord if ":" in coord else f"{coord}:{port}"
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=env.world_size,
                                   process_id=env.rank)
    _initialized = True
    return env


def is_initialized() -> bool:
    return _initialized


def parallel_device_count() -> int:
    return jax.device_count()
