"""Distributed checkpoint with resharding-on-load (reference:
python/paddle/distributed/checkpoint/save_state_dict.py, load_state_dict.py,
metadata.py — shard metadata + dedup of replicated shards, async_save :94).

TPU-native: orbax/OCDBT is the storage engine. Each host serializes only its
addressable shards and replicated arrays are written once (the reference's
dedup_tensor pass); load passes the DESTINATION sharding to orbax so every
device reads exactly its slice from storage — no full-array host gather at any
point. Async save snapshots device→host with non-blocking copies *before*
queueing, so the writer thread never stalls the device stream."""
from __future__ import annotations

import json
import os
import threading
import queue as queue_mod

import numpy as np
import jax

from ...core.tensor import Tensor
from ...core.dispatch import unwrap

# instrumentation: counts full-array host materializations during load
# (tests assert it stays 0 on the sharded path)
_host_gather_count = 0


def _to_arrays(state_dict):
    flat = {}
    for k, v in state_dict.items():
        flat[k] = unwrap(v) if isinstance(v, Tensor) else v
    return flat


def _sharding_desc(a):
    s = getattr(a, "sharding", None)
    if s is None:
        return None
    try:
        return {"spec": str(s.spec), "mesh": dict(zip(s.mesh.axis_names,
                                                      s.mesh.devices.shape))}
    except Exception:
        return str(s)


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    """reference: distributed/checkpoint/save_state_dict.py.

    Sharded jax.Arrays are written shard-wise (replicated shards deduped by
    the storage layer — one copy, not num_devices copies); a sidecar
    metadata.json records global shapes/dtypes/shardings (reference
    metadata.py Metadata/LocalTensorMetadata)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    arrays = _to_arrays(state_dict)
    meta = {k: {"shape": list(np.shape(v)),
                "dtype": str(np.asarray(v).dtype if not hasattr(v, "dtype")
                             else v.dtype),
                "sharding": _sharding_desc(v)}
            for k, v in arrays.items()}
    if async_save:
        # device→host snapshot begins NOW (non-blocking); the writer thread
        # only touches host buffers (reference async_save copies then queues)
        for v in arrays.values():
            if isinstance(v, jax.Array):
                try:
                    v.copy_to_host_async()
                except Exception:
                    pass
        snapshot = {k: _snapshot_for_queue(v) for k, v in arrays.items()}
        _async_queue.put((snapshot, meta, path))
        _ensure_async_worker()
        return
    _write(arrays, meta, path)


def _snapshot_for_queue(v):
    """A buffer the writer thread owns outright.  ``np.asarray`` of a CPU
    ``jax.Array`` can be a ZERO-COPY view and plain ``np.ndarray`` params
    are the caller's own mutable storage — queueing either by reference
    means an in-place update (or donation) right after ``async_save``
    returns silently corrupts the checkpoint being written.  Single-device
    arrays are force-copied to host; multi-device arrays are rebuilt from
    per-shard host copies on their original sharding so the shard-wise
    write path still deduplicates replicas."""
    if isinstance(v, jax.Array):
        if getattr(v.sharding, "num_devices", 1) == 1:
            return np.array(v)                      # copy, never a view
        shards = [jax.device_put(np.array(s.data), s.device)
                  for s in v.addressable_shards]
        return jax.make_array_from_single_device_arrays(
            v.shape, v.sharding, shards)
    return np.array(v)                              # detach from caller


def _write(arrays, meta, path):
    import orbax.checkpoint as ocp
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, arrays, force=True)
    with open(os.path.join(path, "paddle_meta.json"), "w") as f:
        json.dump(meta, f, indent=1)


_async_queue: queue_mod.Queue = queue_mod.Queue()
_async_worker = None


def _ensure_async_worker():
    global _async_worker
    if _async_worker is None or not _async_worker.is_alive():
        def run():
            while True:
                item = _async_queue.get()
                if item is None:
                    break
                arrays, meta, path = item
                _write(arrays, meta, path)
                _async_queue.task_done()
        _async_worker = threading.Thread(target=run, daemon=True)
        _async_worker.start()


def wait_async_save():
    _async_queue.join()


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, offload=False):
    """Load INTO state_dict, restoring each array directly onto the
    destination tensor's sharding — orbax reads per-device slices from
    storage, so a 2×4 destination mesh never materializes the mp=8-saved
    global array on host (reference load_state_dict reads slices per the
    current sharding + reshards)."""
    global _host_gather_count
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()

    restore_args = {}
    for k, dst in state_dict.items():
        if isinstance(dst, Tensor):
            sharding = getattr(dst._data, "sharding", None)
            if sharding is not None:
                restore_args[k] = ocp.ArrayRestoreArgs(
                    sharding=sharding, dtype=dst._data.dtype)
            else:
                restore_args[k] = ocp.RestoreArgs()
        else:
            restore_args[k] = ocp.RestoreArgs()
    restored = ckptr.restore(path, restore_args=restore_args)
    for k, dst in state_dict.items():
        if k not in restored:
            raise KeyError(f"checkpoint at {path} missing key {k}")
        src = restored[k]
        if isinstance(dst, Tensor):
            if isinstance(src, jax.Array) and src.dtype == dst._data.dtype:
                dst._data = src              # already sharded to target
            else:
                _host_gather_count += 1      # small/host fallback path
                arr = jax.numpy.asarray(np.asarray(src),
                                        dtype=dst._data.dtype)
                sharding = getattr(dst._data, "sharding", None)
                if sharding is not None and getattr(sharding, "num_devices",
                                                    1) > 1:
                    arr = jax.device_put(arr, sharding)
                dst._data = arr
        else:
            state_dict[k] = src
    return state_dict


def load_metadata(path):
    """Read the sidecar metadata (reference metadata.py Metadata)."""
    with open(os.path.join(os.path.abspath(path), "paddle_meta.json")) as f:
        return json.load(f)
