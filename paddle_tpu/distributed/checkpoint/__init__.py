"""Distributed checkpoint with resharding-on-load (reference:
python/paddle/distributed/checkpoint/save_state_dict.py, load_state_dict.py —
metadata + dedup of replicated shards, async_save queue :94).

TPU-native: orbax handles sharded array serialization (each host writes its
shards — the dedup/flat-mapping metadata of the reference maps to orbax's
OCDBT format); resharding-on-load = restore with a target sharding.
"""
from __future__ import annotations

import os
import threading
import queue as queue_mod

import numpy as np
import jax

from ...core.tensor import Tensor
from ...core.dispatch import unwrap


def _to_arrays(state_dict):
    flat = {}
    for k, v in state_dict.items():
        flat[k] = unwrap(v) if isinstance(v, Tensor) else v
    return flat


def save_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, async_save=False):
    """reference: distributed/checkpoint/save_state_dict.py."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    arrays = _to_arrays(state_dict)
    if async_save:
        _async_queue.put((arrays, path))
        _ensure_async_worker()
        return
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, arrays, force=True)


_async_queue: queue_mod.Queue = queue_mod.Queue()
_async_worker = None


def _ensure_async_worker():
    global _async_worker
    if _async_worker is None or not _async_worker.is_alive():
        def run():
            import orbax.checkpoint as ocp
            ckptr = ocp.PyTreeCheckpointer()
            while True:
                item = _async_queue.get()
                if item is None:
                    break
                arrays, path = item
                # snapshot to host first so training can mutate freely
                host = {k: np.asarray(v) for k, v in arrays.items()}
                ckptr.save(path, host, force=True)
                _async_queue.task_done()
        _async_worker = threading.Thread(target=run, daemon=True)
        _async_worker.start()


def wait_async_save():
    _async_queue.join()


def load_state_dict(state_dict, path, process_group=None, coordinator_rank=0,
                    unique_id=None, offload=False):
    """Load INTO state_dict, resharding each array to the destination tensor's
    current sharding (reference: load_state_dict.py reads slices per current
    sharding)."""
    import orbax.checkpoint as ocp
    path = os.path.abspath(path)
    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(path)
    for k, dst in state_dict.items():
        if k not in restored:
            raise KeyError(f"checkpoint at {path} missing key {k}")
        src = restored[k]
        if isinstance(dst, Tensor):
            arr = jax.numpy.asarray(np.asarray(src), dtype=dst._data.dtype)
            sharding = getattr(dst._data, "sharding", None)
            if sharding is not None and getattr(sharding, "num_devices", 1) > 1:
                arr = jax.device_put(arr, sharding)  # reshard-on-load
            dst._data = arr
        else:
            state_dict[k] = src
    return state_dict
