"""Host-resident sparse embedding parameter server (reference capability:
paddle/fluid/distributed/ps/table/memory_sparse_table.cc +
ssd_sparse_table.cc + brpc PS services, ~35k LoC — the "100B features"
workload).

TPU framing: dense training scales on XLA collectives; what stays
PS-shaped is embedding tables too large for HBM (or even host RAM). Each
server process owns a row-hash shard as a HASH table (ids are sparse
feature hashes, not [0, rows) indices): hot rows live in a bounded
in-memory pool (LRU), cold rows spill to a per-shard sqlite file (the SSD
table analog), misses initialize on first touch. Optimizers (sgd/adagrad)
run SERVER-side on push, like the reference accessors. Servers speak a
length-prefixed pickle protocol on their own socket — independent of the
trainer world, so a server can be killed and restarted from its
checkpoint while trainers reconnect.

Trainer integration: PsEmbedding pulls rows for the unique ids in the
batch onto device and pushes row gradients from a backward hook.
"""
from __future__ import annotations

import os
import pickle
import socket
import sqlite3
import struct
import threading
import time

import numpy as np

from paddle_tpu.core.hermetic import cpu_child_env as _hermetic_env

__all__ = ["SparseShard", "serve", "start_server_process", "SparsePsClient",
           "PsEmbedding"]


# =============================== server side ================================

class SparseShard:
    """One server's shard of one table: bounded LRU pool + sqlite spill,
    gated by a CtrAccessor-style feature policy (reference:
    paddle/fluid/distributed/ps/table/ctr_accessor.h:30 — show-threshold
    admission, show-score time decay, threshold-based shrink):

      * admission — with ``admit_threshold`` > 0 a feature id gets a trained
        row only after its cumulative push count reaches the threshold;
        earlier pushes only bump a bounded candidate counter (their grads are
        dropped, as the reference drops updates to uncreated embedx), and
        pulls of unadmitted ids return the initializer row without creating
        state.  A skewed stream of one-shot features therefore cannot fill
        the table.
      * score + decay — every push adds to the row's show-score;
        ``shrink(decay_rate, delete_threshold)`` multiplies all scores
        (resident, spilled, candidates) by the decay and deletes rows whose
        score fell below the threshold (the reference's Table::Shrink).
    """

    def __init__(self, name, dim, capacity_rows, data_dir, lr=0.1,
                 optimizer="sgd", initializer="uniform", seed=0,
                 admit_threshold=0):
        self.name = name
        self.dim = int(dim)
        self.capacity = int(capacity_rows)
        self.lr = float(lr)
        self.optimizer = optimizer
        self.initializer = initializer
        self.admit_threshold = int(admit_threshold)
        self._rng = np.random.RandomState(seed)
        os.makedirs(data_dir, exist_ok=True)
        self._db_path = os.path.join(data_dir, f"{name}.spill.sqlite")
        self._db = sqlite3.connect(self._db_path, check_same_thread=False)
        self._db.execute("CREATE TABLE IF NOT EXISTS rows ("
                         "id INTEGER PRIMARY KEY, row BLOB, accum REAL, "
                         "score REAL DEFAULT 0)")
        self._migrate_schema()
        # resident pool: id -> pool slot; LRU tick per slot
        self.pool = np.zeros((self.capacity, self.dim), np.float32)
        self.accum = np.zeros((self.capacity,), np.float32)   # adagrad state
        self.score = np.zeros((self.capacity,), np.float32)   # show-score
        self.slot_of: dict[int, int] = {}
        self.id_of = np.full((self.capacity,), -1, np.int64)
        self.tick_of = np.zeros((self.capacity,), np.int64)
        self._free = list(range(self.capacity - 1, -1, -1))
        self._tick = 0
        # pre-admission candidates: id -> cumulative push count (bounded)
        self._candidates: dict[int, float] = {}
        self._cand_budget = max(8 * self.capacity, 1024)
        # the one fixed row every unadmitted pull returns (reference: missing
        # features pull default values) — drawn ONCE at init so read-only
        # pulls neither perturb the init RNG stream nor return a different
        # vector per call
        self._unadmitted_row = self._init_row()
        self.lock = threading.Lock()

    def _migrate_schema(self):
        """Spill DBs / checkpoints written before the accessor policy have a
        3-column rows table; add the score column in place so old data loads
        (scores start at 0 = coldest, which is the honest prior)."""
        cols = [r[1] for r in self._db.execute("PRAGMA table_info(rows)")]
        if "score" not in cols:
            self._db.execute(
                "ALTER TABLE rows ADD COLUMN score REAL DEFAULT 0")
            self._db.commit()

    # -- row lifecycle --------------------------------------------------------
    def _init_row(self):
        if self.initializer == "zeros":
            return np.zeros((self.dim,), np.float32)
        return (self._rng.rand(self.dim).astype(np.float32) - 0.5) * 0.02

    def _evict_one(self):
        slot = int(np.argmin(self.tick_of))
        rid = int(self.id_of[slot])
        if rid >= 0:
            self._db.execute(
                "INSERT OR REPLACE INTO rows VALUES (?, ?, ?, ?)",
                (rid, self.pool[slot].tobytes(), float(self.accum[slot]),
                 float(self.score[slot])))
            del self.slot_of[rid]
            self._evicted_uncommitted = True
        self.id_of[slot] = -1
        return slot

    def _commit_evictions(self):
        # evicted rows are gone from the pool, so an uncommitted spill INSERT
        # is the only copy — commit at batch boundaries or a crash between
        # checkpoints silently re-initializes them (ADVICE r3)
        if getattr(self, "_evicted_uncommitted", False):
            self._db.commit()
            self._evicted_uncommitted = False

    def _resident(self, rid, create=True):
        """Slot of row `rid`, faulting it in (spill or fresh init).
        ``create=False`` (pull of an unadmitted id) returns None instead of
        creating state for an id that exists nowhere."""
        slot = self.slot_of.get(rid)
        if slot is None:
            cur = self._db.execute(
                "SELECT row, accum, score FROM rows WHERE id=?",
                (rid,)).fetchone()
            if cur is None and not create:
                return None
            slot = self._free.pop() if self._free else self._evict_one()
            if cur is not None:
                self.pool[slot] = np.frombuffer(cur[0], np.float32)
                self.accum[slot] = cur[1]
                self.score[slot] = cur[2]
                self._db.execute("DELETE FROM rows WHERE id=?", (rid,))
            else:
                self.pool[slot] = self._init_row()
                self.accum[slot] = 0.0
                self.score[slot] = 0.0
            self.slot_of[rid] = slot
            self.id_of[slot] = rid
        self._tick += 1
        self.tick_of[slot] = self._tick
        return slot

    # -- serving --------------------------------------------------------------
    def pull(self, ids):
        ids = np.asarray(ids, np.int64)
        out = np.empty((len(ids), self.dim), np.float32)
        with self.lock:
            # with admission gating, a pull must not create state: unadmitted
            # ids get the initializer row (reference: missing feature pulls
            # default values; embedx exists only past the show threshold)
            create = self.admit_threshold <= 0
            for i, rid in enumerate(ids):
                slot = self._resident(int(rid), create=create)
                out[i] = self.pool[slot] if slot is not None \
                    else self._unadmitted_row
            self._commit_evictions()
        return out

    def _admit(self, rid, count):
        """Candidate bookkeeping; True once `rid` may own a trained row."""
        if self.admit_threshold <= 0:
            return True
        if self.slot_of.get(rid) is not None or self._db.execute(
                "SELECT 1 FROM rows WHERE id=?", (rid,)).fetchone():
            return True          # already created
        total = self._candidates.get(rid, 0.0) + count
        if total >= self.admit_threshold:
            self._candidates.pop(rid, None)
            return True
        self._candidates[rid] = total
        if len(self._candidates) > self._cand_budget:
            # bounded candidate set: drop the colder half (one-shot features)
            keep = sorted(self._candidates.items(),
                          key=lambda kv: kv[1],
                          reverse=True)[:self._cand_budget // 2]
            self._candidates = dict(keep)
        return False

    def push(self, ids, grads):
        """Sparse server-side update; duplicate ids accumulate. Updates to
        unadmitted features are dropped (candidate counter bumped instead)."""
        ids = np.asarray(ids, np.int64)
        g = np.asarray(grads, np.float32)
        with self.lock:
            agg: dict[int, np.ndarray] = {}
            cnt: dict[int, int] = {}
            for i, rid in enumerate(ids):
                rid = int(rid)
                agg[rid] = agg.get(rid, 0) + g[i]
                cnt[rid] = cnt.get(rid, 0) + 1
            for rid, gr in agg.items():
                if not self._admit(rid, cnt[rid]):
                    continue
                slot = self._resident(rid)
                self.score[slot] += cnt[rid]
                if self.optimizer == "adagrad":
                    self.accum[slot] += float((gr * gr).mean())
                    scale = self.lr / (np.sqrt(self.accum[slot]) + 1e-8)
                    self.pool[slot] -= scale * gr
                else:
                    self.pool[slot] -= self.lr * gr
            self._commit_evictions()

    def shrink(self, decay_rate=0.98, delete_threshold=None):
        """Decay every show-score by `decay_rate`; with `delete_threshold`,
        drop rows (resident + spilled) and candidates whose score fell below
        it.  Returns the number of rows deleted (Table::Shrink analog)."""
        deleted = 0
        with self.lock:
            self.score[list(self.slot_of.values())] *= decay_rate
            self._db.execute("UPDATE rows SET score = score * ?",
                             (decay_rate,))
            self._candidates = {k: v * decay_rate
                                for k, v in self._candidates.items()
                                if v * decay_rate >= 0.5}
            if delete_threshold is not None:
                for rid in list(self.slot_of):
                    slot = self.slot_of[rid]
                    if self.score[slot] < delete_threshold:
                        del self.slot_of[rid]
                        self.id_of[slot] = -1
                        self.tick_of[slot] = 0
                        self._free.append(slot)
                        deleted += 1
                cur = self._db.execute(
                    "DELETE FROM rows WHERE score < ?", (delete_threshold,))
                deleted += cur.rowcount
            self._db.commit()
        return deleted

    # -- persistence ----------------------------------------------------------
    def save(self, path):
        """Checkpoint = spill EVERYTHING to the sqlite + copy it to `path`
        atomically (reference: table save to afs/local fs)."""
        with self.lock:
            for rid in list(self.slot_of):
                slot = self.slot_of[rid]
                self._db.execute(
                    "INSERT OR REPLACE INTO rows VALUES (?, ?, ?, ?)",
                    (rid, self.pool[slot].tobytes(), float(self.accum[slot]),
                     float(self.score[slot])))
            self._db.commit()
            tmp = path + ".tmp"
            dst = sqlite3.connect(tmp)
            with dst:
                self._db.backup(dst)
            dst.close()
            os.replace(tmp, path)
        return True

    def load(self, path):
        with self.lock:
            src = sqlite3.connect(path)
            self._db.execute("DELETE FROM rows")
            self._db.commit()       # backup needs no open txn on the dest
            src.backup(self._db)
            src.close()
            self._migrate_schema()  # a pre-score-column checkpoint replaces
            # the whole schema via backup(); re-add the column if needed
            self.slot_of.clear()
            self.id_of[:] = -1
            self.tick_of[:] = 0
            self._free = list(range(self.capacity - 1, -1, -1))
        return True

    def stats(self):
        with self.lock:
            spilled = self._db.execute("SELECT COUNT(*) FROM rows").fetchone()[0]
            return {"resident": len(self.slot_of), "spilled": int(spilled),
                    "capacity": self.capacity, "dim": self.dim,
                    "candidates": len(self._candidates),
                    "admit_threshold": self.admit_threshold}


def _auth_key():
    """Shared wire key (PADDLE_PS_AUTH_KEY). The protocol is pickle, so an
    unauthenticated frame is arbitrary code execution for anyone who can
    reach the port — with a key set, every frame carries an HMAC-SHA256 that
    is verified BEFORE unpickling, and unauthenticated peers are dropped."""
    k = os.environ.get("PADDLE_PS_AUTH_KEY", "")
    return k.encode() if k else None


class _AuthError(Exception):
    pass


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = struct.unpack("!Q", hdr)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    body = bytes(buf)
    key = _auth_key()
    if key is not None:
        import hashlib
        import hmac as _hmac
        if len(body) < 32 or not _hmac.compare_digest(
                body[:32], _hmac.new(key, body[32:], hashlib.sha256).digest()):
            raise _AuthError("PS frame failed HMAC verification")
        body = body[32:]
    return pickle.loads(body)


def _send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    key = _auth_key()
    if key is not None:
        import hashlib
        import hmac as _hmac
        payload = _hmac.new(key, payload, hashlib.sha256).digest() + payload
    sock.sendall(struct.pack("!Q", len(payload)) + payload)


def serve(port, data_dir, host="127.0.0.1", ready_file=None, load_dir=None):
    """Run a PS server (blocking): one process = one shard of every table.
    With `load_dir`, a table whose shard checkpoint exists there warm-starts
    from it on create (fleet.init_server(dirname) analog)."""
    os.makedirs(data_dir, exist_ok=True)
    shards: dict[str, SparseShard] = {}
    create_lock = threading.Lock()  # create is idempotent under concurrency
    stop = threading.Event()

    def handle(conn):
        try:
            while not stop.is_set():
                try:
                    msg = _recv_msg(conn)
                except _AuthError:
                    # unauthenticated/forged frame: drop the peer without
                    # replying (and without ever having unpickled its bytes)
                    import logging
                    logging.getLogger("paddle_tpu.ps_sparse").warning(
                        "rejected unauthenticated frame")
                    return
                if msg is None:
                    return
                op = msg["op"]
                try:
                    if op == "create":
                        name = msg["name"]
                        # check-then-insert must be atomic: concurrent trainer
                        # connects run in separate handler threads, and a
                        # double-create would silently discard pushes applied
                        # to the replaced shard (ADVICE r3)
                        with create_lock:
                            if name not in shards:
                                sh = SparseShard(
                                    name, msg["dim"], msg["capacity"],
                                    data_dir,
                                    lr=msg.get("lr", 0.1),
                                    optimizer=msg.get("optimizer", "sgd"),
                                    initializer=msg.get("initializer",
                                                        "uniform"),
                                    seed=msg.get("seed", 0),
                                    admit_threshold=msg.get(
                                        "admit_threshold", 0))
                                if load_dir:
                                    ck = os.path.join(
                                        load_dir, f"{name}.shard.sqlite")
                                    if os.path.exists(ck):
                                        sh.load(ck)
                                shards[name] = sh
                        _send_msg(conn, {"ok": True})
                    elif op == "pull":
                        _send_msg(conn, {"ok": True, "rows":
                                         shards[msg["name"]].pull(msg["ids"])})
                    elif op == "push":
                        shards[msg["name"]].push(msg["ids"], msg["grads"])
                        _send_msg(conn, {"ok": True})
                    elif op == "save":
                        for name, sh in shards.items():
                            sh.save(os.path.join(
                                msg["path"], f"{name}.shard.sqlite"))
                        _send_msg(conn, {"ok": True})
                    elif op == "load":
                        name = msg["name"]
                        shards[name].load(os.path.join(
                            msg["path"], f"{name}.shard.sqlite"))
                        _send_msg(conn, {"ok": True})
                    elif op == "shrink":
                        names = ([msg["name"]] if msg.get("name")
                                 else list(shards))
                        _send_msg(conn, {"ok": True, "deleted": {
                            n: shards[n].shrink(
                                decay_rate=msg.get("decay_rate", 0.98),
                                delete_threshold=msg.get("delete_threshold"))
                            for n in names}})
                    elif op == "stats":
                        _send_msg(conn, {"ok": True, "stats": {
                            n: s.stats() for n, s in shards.items()}})
                    elif op == "shutdown":
                        _send_msg(conn, {"ok": True})
                        stop.set()
                        return
                    else:
                        _send_msg(conn, {"ok": False,
                                         "error": f"unknown op {op}"})
                except Exception as e:   # noqa: BLE001 — report to client
                    _send_msg(conn, {"ok": False, "error": repr(e)})
        finally:
            conn.close()

    srv = socket.socket()
    try:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(64)
        if ready_file:
            # the launcher polls for this file's existence; publish it
            # atomically so it can never observe an empty/torn pid
            with open(ready_file + ".tmp", "w") as f:
                f.write(str(os.getpid()))
            os.replace(ready_file + ".tmp", ready_file)
        srv.settimeout(0.2)
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            threading.Thread(target=handle, args=(conn,),
                             daemon=True).start()
    finally:
        # bind failure (port in use) or a ready-file error must not leak
        # the listener fd
        srv.close()


def start_server_process(port, data_dir, ready_timeout=30.0):
    """Spawn a PS server as a child process; returns the Popen handle."""
    import subprocess
    import sys
    ready = os.path.join(data_dir, f"ps_ready_{port}.txt")
    if os.path.exists(ready):
        os.remove(ready)
    p = subprocess.Popen(
        [sys.executable, "-c",
         "import sys; sys.path.insert(0, %r); "
         "from paddle_tpu.distributed.ps_sparse import serve; "
         "serve(%d, %r, ready_file=%r)" % (
             os.path.dirname(os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))), port, data_dir, ready)],
        env=_hermetic_env())
    deadline = time.monotonic() + ready_timeout
    while time.monotonic() < deadline:
        if os.path.exists(ready):
            return p
        if p.poll() is not None:
            raise RuntimeError(f"PS server on port {port} died at startup")
        time.sleep(0.05)
    raise TimeoutError(f"PS server on port {port} not ready")


# =============================== client side ================================

class SparsePsClient:
    """Trainer handle to N shard servers; reconnects on failure so a killed
    and restarted server resumes transparently."""

    def __init__(self, endpoints, retry=30.0):
        self.endpoints = [(h, int(p)) for h, p in
                          (e.split(":") for e in endpoints)]
        self._socks: list = [None] * len(self.endpoints)
        self.retry = retry

    def _sock(self, si):
        if self._socks[si] is None:
            deadline = time.monotonic() + self.retry
            while True:
                try:
                    s = socket.create_connection(self.endpoints[si],
                                                 timeout=5)
                    try:
                        s.setsockopt(socket.IPPROTO_TCP,
                                     socket.TCP_NODELAY, 1)
                        s.settimeout(None)
                    except OSError:
                        # the retry loop would otherwise leak one connected
                        # fd per failed attempt
                        s.close()
                        raise
                    self._socks[si] = s
                    break
                except OSError:
                    if time.monotonic() > deadline:
                        raise
                    time.sleep(0.1)
        return self._socks[si]

    def _call(self, si, msg):
        deadline = time.monotonic() + self.retry
        while True:
            try:
                s = self._sock(si)
                _send_msg(s, msg)
                rep = _recv_msg(s)
                if rep is None:
                    raise ConnectionError("server closed")
                if not rep.get("ok"):
                    raise RuntimeError(rep.get("error"))
                return rep
            except (ConnectionError, OSError):
                self._socks[si] = None       # reconnect (restarted server)
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)

    # -- table API ------------------------------------------------------------
    def create_table(self, name, dim, capacity_rows_per_server, lr=0.1,
                     optimizer="sgd", initializer="uniform",
                     admit_threshold=0):
        for si in range(len(self.endpoints)):
            self._call(si, {"op": "create", "name": name, "dim": dim,
                            "capacity": capacity_rows_per_server, "lr": lr,
                            "optimizer": optimizer,
                            "initializer": initializer, "seed": si,
                            "admit_threshold": admit_threshold})

    def shrink(self, name=None, decay_rate=0.98, delete_threshold=None):
        """Decay feature scores on every server (CtrAccessor show-decay) and
        delete rows below `delete_threshold`. Returns total rows deleted."""
        total = 0
        for si in range(len(self.endpoints)):
            rep = self._call(si, {"op": "shrink", "name": name,
                                  "decay_rate": decay_rate,
                                  "delete_threshold": delete_threshold})
            total += sum(rep["deleted"].values())
        return total

    def _split(self, ids):
        ids = np.asarray(ids, np.int64)
        shard = ids % len(self.endpoints)
        return [(si, np.nonzero(shard == si)[0], ids[shard == si])
                for si in range(len(self.endpoints))]

    def pull(self, name, ids):
        ids = np.asarray(ids, np.int64)
        out = None
        for si, pos, sub in self._split(ids):
            if not len(sub):
                continue
            rows = self._call(si, {"op": "pull", "name": name,
                                   "ids": sub})["rows"]
            if out is None:
                out = np.empty((len(ids), rows.shape[1]), np.float32)
            out[pos] = rows
        return out

    def push(self, name, ids, grads):
        g = np.asarray(grads, np.float32)
        for si, pos, sub in self._split(ids):
            if len(sub):
                self._call(si, {"op": "push", "name": name, "ids": sub,
                                "grads": g[pos]})

    def save(self, path):
        os.makedirs(path, exist_ok=True)
        for si in range(len(self.endpoints)):
            d = os.path.join(path, f"server_{si}")
            os.makedirs(d, exist_ok=True)
            self._call(si, {"op": "save", "path": d})

    def load(self, name, path):
        for si in range(len(self.endpoints)):
            self._call(si, {"op": "load", "name": name,
                            "path": os.path.join(path, f"server_{si}")})

    def stats(self):
        return [self._call(si, {"op": "stats"})["stats"]
                for si in range(len(self.endpoints))]

    def close(self, si=None):
        """Drop client connections (servers keep running)."""
        for i in ([si] if si is not None else range(len(self.endpoints))):
            s = self._socks[i]
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
            self._socks[i] = None

    def shutdown(self, si=None):
        for i in ([si] if si is not None else range(len(self.endpoints))):
            try:
                self._call(i, {"op": "shutdown"})
            except Exception:
                pass
        self.close(si)


# ============================ device integration ============================

class PsEmbedding:
    """Embedding lookup against a PS table (reference: the PS-mode
    paddle.static.nn.sparse_embedding).

    forward: unique ids in the batch -> pull rows (host) -> device gather.
    backward: a hook on the pulled-rows leaf tensor pushes per-row grads
    back to the servers (server-side optimizer applies them), so the
    embedding "trains" without the table ever living on device.

    Caveats (by design, matching the reference's PS semantics): the push
    happens DURING backward, so PS rows bypass trainer-side gradient
    clipping; under AMP the hook divides by the active GradScaler's current
    loss scale (amp.active_loss_scale) since unscale_() has not run yet."""

    def __init__(self, client, table, dim, lr=0.1, optimizer="sgd",
                 capacity_rows_per_server=2 ** 20):
        self.client = client
        self.table = table
        self.dim = dim
        client.create_table(table, dim,
                            capacity_rows_per_server=capacity_rows_per_server,
                            lr=lr, optimizer=optimizer)

    def __call__(self, ids):
        from ..core.tensor import Tensor
        from ..ops import manipulation as _m  # noqa: F401 (op registry)
        import paddle_tpu as paddle
        ids_np = np.asarray(ids.numpy() if hasattr(ids, "numpy") else ids,
                            np.int64)
        flat = ids_np.reshape(-1)
        uniq, inv = np.unique(flat, return_inverse=True)
        rows_np = self.client.pull(self.table, uniq)
        rows = Tensor(np.asarray(rows_np), stop_gradient=False)
        client, table = self.client, self.table

        def _push(grad):
            from ..amp import active_loss_scale
            g = np.asarray(grad._data if hasattr(grad, "_data") else grad,
                           np.float32)
            scale = active_loss_scale()
            if scale != 1.0:   # AMP: grads are still loss-scale-multiplied
                g = g / scale
            if not np.isfinite(g).all():
                # fp16 overflow step: GradScaler will skip the dense update;
                # skipping the push keeps PS rows equally protected (a pushed
                # inf would poison the table permanently)
                return grad
            client.push(table, uniq, g)
            return grad

        rows.register_hook(_push)
        gathered = rows[paddle.to_tensor(inv.astype(np.int32))]
        return gathered.reshape(list(ids_np.shape) + [self.dim])
