"""Explicit collective API (reference: python/paddle/distributed/communication/
+ ProcessGroup contract phi/core/distributed/collective/process_group.h:130).

TPU-native mapping (SURVEY §5): in the hot path collectives are emitted by GSPMD
inside jit; this module provides the *explicit* eager surface. Groups map to
sub-sets of the global mesh. Within one process, a "rank" is a device: eager
collectives over sharded tensors run a tiny jitted shard_map(psum/all_gather...).
Across processes (multi-host), object-level collectives use JAX's coordination
service (multihost_utils).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..core.dispatch import unwrap
from .. import observability as _obs
from .env import get_rank, get_world_size


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """reference: communication/group.py Group."""

    _next_id = 0

    def __init__(self, ranks, name=None):
        self.ranks = list(ranks)
        self.id = Group._next_id
        Group._next_id += 1
        self.name = name or f"group_{self.id}"

    @property
    def nranks(self):
        return len(self.ranks)

    @property
    def world_size(self):
        return len(self.ranks)

    def get_rank(self):
        r = get_rank()
        return self.ranks.index(r) if r in self.ranks else -1

    def get_world_size(self):
        return len(self.ranks)

    @property
    def rank(self):
        return self.get_rank()

    def get_group_rank(self, global_rank):
        return self.ranks.index(global_rank) if global_rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, ranks={self.ranks})"


_groups: dict[int, Group] = {}
_global_group: Group | None = None


def _get_global_group() -> Group:
    global _global_group
    if _global_group is None:
        _global_group = Group(list(range(get_world_size())), name="global")
        _groups[_global_group.id] = _global_group
    return _global_group


def new_group(ranks=None, backend=None, timeout=None):
    g = Group(ranks if ranks is not None else list(range(get_world_size())))
    _groups[g.id] = g
    return g


def split_group(parent=None, split_sizes=None):
    parent = parent or _get_global_group()
    out = []
    start = 0
    for s in split_sizes:
        out.append(new_group(parent.ranks[start:start + s]))
        start += s
    return out


def get_group(gid=0):
    return _groups.get(gid, _get_global_group())


def is_available():
    return True


def _is_sharded(arr) -> bool:
    sharding = getattr(arr, "sharding", None)
    return sharding is not None and getattr(sharding, "num_devices", 1) > 1


def _device_allreduce(arr, op):
    """Reduce a device-sharded array in place across its mesh (replicated out)."""
    sharding = arr.sharding
    mesh = sharding.mesh
    repl = NamedSharding(mesh, P())
    if op == ReduceOp.SUM or op == ReduceOp.AVG:
        # sum of shards = unshard to replicated then psum? device_put gathers, it
        # does NOT reduce — a sharded array's global value already includes all
        # shards. Explicit allreduce semantics apply to *independent per-rank*
        # values, which in single-controller JAX only exist under shard_map.
        return jax.device_put(arr, repl)
    return jax.device_put(arr, repl)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """In-place allreduce. World size 1 (single controller): identity —
    a Tensor is already a *global* value in the JAX programming model; per-rank
    partial values only arise under shard_map (used by the parallel layers)."""
    g = group or _get_global_group()
    if g.get_world_size() <= 1 or jax.process_count() == 1:
        return tensor
    from jax.experimental import multihost_utils
    arr = unwrap(tensor)
    summed = multihost_utils.process_allgather(arr)
    if op == ReduceOp.SUM:
        out = jnp.sum(summed, axis=0)
    elif op == ReduceOp.MAX:
        out = jnp.max(summed, axis=0)
    elif op == ReduceOp.MIN:
        out = jnp.min(summed, axis=0)
    elif op == ReduceOp.AVG:
        out = jnp.mean(summed, axis=0)
    else:
        out = jnp.prod(summed, axis=0)
    tensor._data = out.astype(arr.dtype)
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    g = group or _get_global_group()
    if g.get_world_size() <= 1 or jax.process_count() == 1:
        tensor_list.append(Tensor(unwrap(tensor)))
        return tensor_list
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(unwrap(tensor))
    for i in range(gathered.shape[0]):
        tensor_list.append(Tensor(gathered[i]))
    return tensor_list


def all_gather_object(object_list, obj, group=None):
    g = group or _get_global_group()
    if g.get_world_size() <= 1 or jax.process_count() == 1:
        object_list.append(obj)
        return object_list
    import pickle
    from jax.experimental import multihost_utils
    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    # pad to max length across processes
    n = np.asarray([data.size], np.int64)
    sizes = multihost_utils.process_allgather(n).reshape(-1)
    maxlen = int(sizes.max())
    padded = np.zeros(maxlen, np.uint8)
    padded[:data.size] = data
    all_data = multihost_utils.process_allgather(padded)
    for i, s in enumerate(sizes):
        object_list.append(pickle.loads(bytes(np.asarray(all_data[i][:int(s)]))))
    return object_list


def broadcast(tensor, src=0, group=None, sync_op=True):
    g = group or _get_global_group()
    if g.get_world_size() <= 1 or jax.process_count() == 1:
        return tensor
    from jax.experimental import multihost_utils
    out = multihost_utils.broadcast_one_to_all(unwrap(tensor),
                                               is_source=get_rank() == src)
    tensor._data = out
    return tensor


def broadcast_object_list(object_list, src=0, group=None):
    g = group or _get_global_group()
    if g.get_world_size() <= 1 or jax.process_count() == 1:
        return object_list
    import pickle
    from jax.experimental import multihost_utils
    if get_rank() == src:
        data = np.frombuffer(pickle.dumps(object_list), dtype=np.uint8)
        size = np.asarray([data.size], np.int64)
    else:
        data = np.zeros(1, np.uint8)
        size = np.asarray([0], np.int64)
    size = multihost_utils.broadcast_one_to_all(size, is_source=get_rank() == src)
    buf = np.zeros(int(size[0]), np.uint8)
    if get_rank() == src:
        buf[:] = data
    buf = multihost_utils.broadcast_one_to_all(buf, is_source=get_rank() == src)
    if get_rank() != src:
        object_list[:] = pickle.loads(bytes(np.asarray(buf)))
    return object_list


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Explicit scatter. Cross-process: src broadcasts the stacked payload and
    every rank keeps its slice. This is the *explicit-API* path for control
    data; bulk compute scatters are GSPMD shardings (shard_tensor)."""
    g = group or _get_global_group()
    if g.get_world_size() <= 1 or jax.process_count() == 1:
        if tensor_list:
            tensor._data = unwrap(tensor_list[0])
        return tensor
    from jax.experimental import multihost_utils
    me = get_rank()
    world = jax.process_count()
    if me == src:
        stacked = np.stack([np.asarray(unwrap(t)) for t in tensor_list])
    else:
        one = np.asarray(unwrap(tensor))
        stacked = np.zeros((world,) + one.shape, one.dtype)
    stacked = multihost_utils.broadcast_one_to_all(stacked,
                                                   is_source=me == src)
    tensor._data = jnp.asarray(np.asarray(stacked)[me])
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    """Explicit all-to-all: allgather the stacked per-destination payloads,
    then each rank keeps column [*, me]. Compute-plane a2a (MoE dispatch) is
    GSPMD inside shard_map — this is the explicit-API/control path."""
    g = group or _get_global_group()
    if g.get_world_size() <= 1 or jax.process_count() == 1:
        out_tensor_list.extend(Tensor(unwrap(t)) for t in in_tensor_list)
        return out_tensor_list
    from jax.experimental import multihost_utils
    me = get_rank()
    stacked = np.stack([np.asarray(unwrap(t)) for t in in_tensor_list])
    gathered = np.asarray(multihost_utils.process_allgather(stacked))
    out_tensor_list.extend(Tensor(jnp.asarray(gathered[srcr, me]))
                           for srcr in range(jax.process_count()))
    return out_tensor_list


def reduce_scatter(tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    g = group or _get_global_group()
    if g.get_world_size() <= 1 or jax.process_count() == 1:
        acc = unwrap(tensor_list[0])
        for t in tensor_list[1:]:
            acc = acc + unwrap(t)
        tensor._data = acc
        return tensor
    from jax.experimental import multihost_utils
    me = get_rank()
    stacked = np.stack([np.asarray(unwrap(t)) for t in tensor_list])
    gathered = np.asarray(multihost_utils.process_allgather(stacked))
    red = _np_reduce(gathered, op, axis=0)            # [world, ...] per-dst
    tensor._data = jnp.asarray(red[me])
    return tensor


def _np_reduce(arr, op, axis):
    if op == ReduceOp.SUM:
        return arr.sum(axis=axis)
    if op == ReduceOp.MAX:
        return arr.max(axis=axis)
    if op == ReduceOp.MIN:
        return arr.min(axis=axis)
    if op == ReduceOp.PROD:
        return arr.prod(axis=axis)
    raise ValueError(f"unsupported reduce op {op}")


_p2p_seq = {}


def _store_or_raise():
    from .env import get_store
    store = get_store()
    if store is None:
        raise RuntimeError(
            "send/recv need init_parallel_env() in a multi-process job "
            "(the TCPStore control plane is not up)")
    return store


_local_p2p: dict = {}


def send(tensor, dst=0, group=None, sync_op=True):
    """P2P send over the TCPStore control plane (reference send over NCCL;
    on TPU the compute plane uses ppermute inside shard_map — see
    parallel/pipeline — so explicit send/recv is host-side by design)."""
    import pickle
    me = get_rank()
    if dst == me and jax.process_count() == 1:   # self-send loopback
        k = ("loop", me)
        _local_p2p.setdefault(k, []).append(np.asarray(unwrap(tensor)))
        return tensor
    store = _store_or_raise()
    k = ("send", me, dst)
    seq = _p2p_seq.get(k, 0)
    _p2p_seq[k] = seq + 1
    arr = np.asarray(unwrap(tensor))
    _warn_large_p2p(arr.nbytes)
    store.set(f"p2p/{me}->{dst}/{seq}", pickle.dumps(arr))
    return tensor


_P2P_WARN_BYTES = 16 * 1024 * 1024
_p2p_warned = False


def _warn_large_p2p(nbytes):
    """send/recv are a CONTROL plane (pickle over the TCPStore) — fine for
    small messages, ~1000x slower than ICI for activations. Warn once so a
    user porting NCCL-style activation passing finds the compiled path
    (shard_map + ppermute) instead of silent slowness."""
    global _p2p_warned
    if nbytes > _P2P_WARN_BYTES and not _p2p_warned:
        _p2p_warned = True
        import warnings
        warnings.warn(
            f"dist.send/recv moved a {nbytes/1e6:.0f} MB tensor over the "
            "TCPStore control plane; for activation-sized transfers use the "
            "compiled collectives (shard_map + ppermute / all_to_all) which "
            "ride ICI", RuntimeWarning, stacklevel=3)


def recv(tensor, src=0, group=None, sync_op=True):
    import pickle
    me = get_rank()
    if src == me and jax.process_count() == 1:   # self-recv loopback
        q = _local_p2p.get(("loop", me), [])
        if not q:
            raise RuntimeError("recv from self with nothing sent")
        tensor._data = jnp.asarray(q.pop(0))
        return tensor
    store = _store_or_raise()
    k = ("recv", src, me)
    seq = _p2p_seq.get(k, 0)
    _p2p_seq[k] = seq + 1
    key = f"p2p/{src}->{me}/{seq}"
    arr = pickle.loads(store.get(key))
    store.delete_key(key)
    tensor._data = jnp.asarray(arr)
    return tensor


def barrier(group=None):
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("paddle_tpu_barrier")


def all_reduce_grads(parameters, group=None):
    for p in parameters:
        if p.grad is not None:
            all_reduce(p.grad, ReduceOp.SUM, group)
            ws = (group or _get_global_group()).get_world_size()
            if ws > 1:
                p.grad._data = unwrap(p.grad) / ws


# in-mesh collective helpers used by parallel layers under shard_map ----------
# Each helper meters itself via record_collective(traced=True): the tick
# happens at TRACE time (once per compiled program, not per device execution)
# with per-shard payload bytes from the tracer's aval.
def mesh_all_reduce(x, axis_name, op="sum"):
    _obs.record_collective("mesh_all_reduce", payload=x)
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    if op == "avg":
        return jax.lax.pmean(x, axis_name)
    raise ValueError(op)


def mesh_all_gather(x, axis_name, axis=0):
    _obs.record_collective("mesh_all_gather", payload=x)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)


def mesh_reduce_scatter(x, axis_name, axis=0):
    _obs.record_collective("mesh_reduce_scatter", payload=x)
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def mesh_all_to_all(x, axis_name, split_axis, concat_axis):
    _obs.record_collective("mesh_all_to_all", payload=x)
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def mesh_ppermute(x, axis_name, perm):
    _obs.record_collective("mesh_ppermute", payload=x)
    return jax.lax.ppermute(x, axis_name, perm)


# ---- watchdog instrumentation (reference comm_task_manager.h:37) -------------
from .watchdog import watched as _watched  # noqa: E402


def _metered(fn):
    """Count invocation + payload bytes of an explicit eager collective in the
    observability registry (single bool check while telemetry is off)."""
    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        if _obs.enabled():
            payload = next((unwrap(a) for a in args if isinstance(a, Tensor)),
                           None)
            _obs.record_collective(fn.__name__, payload=payload, traced=False)
        return fn(*args, **kwargs)
    return wrapper


all_reduce = _watched(_metered(all_reduce))
all_gather = _watched(_metered(all_gather))
broadcast = _watched(_metered(broadcast))
reduce = _watched(_metered(reduce))
scatter = _watched(_metered(scatter))
all_to_all = _watched(_metered(all_to_all))
reduce_scatter = _watched(_metered(reduce_scatter))
send = _watched(_metered(send))
recv = _watched(_metered(recv))
barrier = _watched(_metered(barrier))


# ---- API-parity wrappers (reference: distributed/communication/*) -----------
alltoall = all_to_all      # reference exposes both names


def all_to_all_single(out_tensor, in_tensor, out_split_sizes=None,
                      in_split_sizes=None, group=None, sync_op=True):
    """reference: communication/all_to_all.py alltoall_single — a single
    tensor split row-wise across ranks."""
    g = group or _get_global_group()
    world = g.get_world_size()
    if world <= 1 or jax.process_count() == 1:
        out_tensor._data = unwrap(in_tensor)
        return out_tensor
    parts = ops_split_rows(in_tensor, in_split_sizes, world)
    outs = [Tensor(np.zeros(1, np.float32)) for _ in range(world)]
    all_to_all(outs, parts, group=group)
    import jax.numpy as _jnp
    out_tensor._data = _jnp.concatenate([unwrap(t) for t in outs], axis=0)
    return out_tensor


def ops_split_rows(tensor, split_sizes, world):
    a = unwrap(tensor)
    if split_sizes:
        idx = np.cumsum(split_sizes)[:-1]
        chunks = np.split(np.asarray(a), idx, axis=0)
    else:
        chunks = np.split(np.asarray(a), world, axis=0)
    import jax.numpy as _jnp
    return [Tensor(_jnp.asarray(c)) for c in chunks]


def gather(tensor, gather_list=None, dst=0, group=None, sync_op=True):
    """reference: communication/gather.py — all ranks contribute, dst gets
    the list (on the single-controller plane every process materializes)."""
    out = []
    all_gather(out, tensor, group=group)
    if gather_list is not None and get_rank() == dst:
        gather_list.extend(out)
    return gather_list if get_rank() == dst else None


def gather_object(obj, object_list=None, dst=0, group=None):
    out = []
    all_gather_object(out, obj, group=group)
    if object_list is not None and get_rank() == dst:
        object_list.extend(out)
    return object_list if get_rank() == dst else None


def scatter_object_list(out_object_list, in_object_list=None, src=0,
                        group=None):
    """reference: communication/scatter.py scatter_object_list."""
    g = group or _get_global_group()
    world = g.get_world_size()
    if world <= 1 or jax.process_count() == 1:
        out_object_list.append(in_object_list[0] if in_object_list else None)
        return out_object_list
    gathered = []
    all_gather_object(gathered, in_object_list if get_rank() == src else
                      None, group=group)
    src_list = gathered[src]
    out_object_list.append(src_list[get_rank()])
    return out_object_list


class _Work:
    """Completed-work handle (reference: async Task.wait() contract; the
    store-based P2P plane completes synchronously, so wait() is a no-op)."""

    def __init__(self, result=None):
        self._result = result

    def wait(self, timeout=None):
        return True

    def is_completed(self):
        return True


def isend(tensor, dst=0, group=None):
    send(tensor, dst=dst, group=group, sync_op=False)
    return _Work(tensor)


def irecv(tensor, src=0, group=None):
    recv(tensor, src=src, group=group, sync_op=False)
    return _Work(tensor)


def wait(tensor, group=None, use_calc_stream=True):
    """reference: communication/wait.py — XLA's async dispatch makes this a
    device sync on the tensor."""
    import jax as _jax
    _jax.block_until_ready(unwrap(tensor))
    return tensor


class P2POp:
    """reference: communication/batch_isend_irecv.py P2POp."""

    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    """reference: batch_isend_irecv — issue sends first so the store always
    has the payloads before any blocking recv."""
    works = []
    sends = [p for p in p2p_op_list if p.op in (isend, send, "isend")]
    recvs = [p for p in p2p_op_list if p not in sends]
    for p in sends:
        works.append(isend(p.tensor, dst=p.peer, group=p.group))
    for p in recvs:
        works.append(irecv(p.tensor, src=p.peer, group=p.group))
    return works


def destroy_process_group(group=None):
    """reference: communication/group.py destroy_process_group."""
    global _groups
    try:
        if group is None:
            _groups.clear()
        else:
            _groups.pop(getattr(group, "id", None), None)
    except NameError:
        pass
