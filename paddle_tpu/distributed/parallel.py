"""DataParallel wrapper (reference: python/paddle/distributed/parallel.py:219 +
EagerReducer fluid/distributed/collective/reducer.h:88).

TPU-native story: under jit, gradients of a batch-sharded loss are reduced by
GSPMD automatically — no bucketed allreduce needed. Eagerly (multi-process),
grad hooks run psum via the collective API after backward.
"""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .env import get_world_size


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False, group=None):
        super().__init__()
        self._layers = layers
        self.group = group
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        """Average grads across data-parallel ranks (explicit eager path)."""
        ws = get_world_size(self.group)
        if ws <= 1:
            return
        from .collective import all_reduce_grads
        all_reduce_grads(self.parameters(), group=self.group)

    # delegate attribute access to the wrapped model
    def __getattr__(self, name):
        try:
            return super().__getattr__(name)
        except AttributeError:
            return getattr(self.__dict__["_sub_layers"]["_layers"], name)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)
