"""paddle.distributed.launch analog (reference launch/main.py:23)."""
from .main import launch  # noqa: F401
