# graftlint: disable-file=no-adhoc-telemetry  (CLI front-end: stdout is the UI)
"""Multi-process launcher (reference: python/paddle/distributed/launch/main.py:23
+ controllers/collective.py). Spawns one worker process per device/slot, wires
the rendezvous env (coordinator address + rank/world), tees per-rank logs, and
supervises: any worker failure tears the job down (or restarts it when
--max_restarts > 0 — the elastic manager's restart loop,
reference fleet/elastic/manager.py:125).

Usage:
    python -m paddle_tpu.distributed.launch --nproc_per_node=2 train.py ...
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse(argv=None):
    p = argparse.ArgumentParser(prog="paddle_tpu.distributed.launch")
    p.add_argument("--nproc_per_node", "--nprocs", type=int, default=None,
                   help="workers on this node (default: local device count)")
    p.add_argument("--nnodes", type=int, default=1)
    p.add_argument("--node_rank", type=int,
                   default=int(os.environ.get("PADDLE_NODE_RANK", "0")))
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER",
                                                      "127.0.0.1:8476"),
                   help="coordinator host:port (rank-0 node)")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="elastic: restart the whole local group this many "
                        "times on worker failure")
    p.add_argument("--backend", default=None,
                   help="set JAX_PLATFORMS for workers (e.g. cpu)")
    p.add_argument("--backend_probe_timeout", type=float, default=90.0,
                   help="before spawning accelerator workers, verify the "
                        "backend initializes in a throwaway child within "
                        "this many seconds — a dead/unreachable tunnel then "
                        "fails the launch immediately with one clear error "
                        "instead of N workers hanging to their timeouts. "
                        "0 disables the probe.")
    p.add_argument("script")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args, local_rank):
    world = args.nnodes * args.nproc_per_node
    rank = args.node_rank * args.nproc_per_node + local_rank
    host, port = (args.master.split(":") + ["8476"])[:2]
    if args.backend == "cpu":
        # CPU-bound workers must not attach the parent's accelerator plugin
        # (it ignores JAX_PLATFORMS and would dial the tunnel at import).
        from paddle_tpu.core.hermetic import cpu_child_env
        env = cpu_child_env()
    else:
        env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_MASTER": args.master,
        "MASTER_ADDR": host,
        "MASTER_PORT": port,
        "PADDLE_CURRENT_ENDPOINT": f"{host}:{int(port) + 1 + rank}",
        "PADDLE_TRAINER_ENDPOINTS": ",".join(
            f"{host}:{int(port) + 1 + r}" for r in range(world)),
        "FLAGS_selected_tpus": str(local_rank),
    })
    if args.backend:
        env["JAX_PLATFORMS"] = args.backend
    return env


def _spawn_all(args):
    os.makedirs(args.log_dir, exist_ok=True)
    procs, logs = [], []
    for lr in range(args.nproc_per_node):
        rank = args.node_rank * args.nproc_per_node + lr
        logf = open(os.path.join(args.log_dir, f"workerlog.{rank}"), "ab")
        cmd = [sys.executable, "-u", args.script] + args.script_args
        p = subprocess.Popen(cmd, env=_worker_env(args, lr),
                             stdout=logf, stderr=subprocess.STDOUT)
        procs.append(p)
        logs.append(logf)
    return procs, logs


def _supervise(procs):
    """Wait for all; on first failure kill the rest. Returns worst rc."""
    pending = {p.pid: p for p in procs}
    rc = 0
    while pending:
        time.sleep(0.2)
        for pid, p in list(pending.items()):
            r = p.poll()
            if r is None:
                continue
            del pending[pid]
            if r != 0:
                rc = rc or r
                for q in pending.values():
                    try:
                        q.send_signal(signal.SIGTERM)
                    except OSError:
                        pass
    return rc


def _probe_backend(timeout):
    """True if a fresh interpreter can initialize the accelerator backend.
    Runs in a child so a hang/failure never wedges the launcher itself."""
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.devices(); print('BACKEND_READY')"],
        capture_output=True, text=True, timeout=timeout)
    return r.returncode == 0 and "BACKEND_READY" in r.stdout


def launch(argv=None):
    args = _parse(argv)
    probe_accel = (args.backend != "cpu"
                   and args.backend_probe_timeout > 0
                   and os.environ.get("PALLAS_AXON_POOL_IPS"))
    if probe_accel:
        try:
            ok = _probe_backend(args.backend_probe_timeout)
        except subprocess.TimeoutExpired:
            ok = False
        if not ok:
            print("launch: accelerator backend failed to initialize within "
                  f"{args.backend_probe_timeout:.0f}s (tunnel down or chip "
                  "held by another process). Fix the backend, or run on CPU "
                  "with --backend cpu, or skip this check with "
                  "--backend_probe_timeout 0.", file=sys.stderr)
            return 3
    if args.nproc_per_node is None:
        try:
            import jax
            args.nproc_per_node = max(1, jax.local_device_count())
        except Exception:
            args.nproc_per_node = 1
    attempt = 0
    while True:
        procs, logs = _spawn_all(args)
        rc = _supervise(procs)
        for f in logs:
            f.close()
        if rc == 0:
            return 0
        if attempt >= args.max_restarts:
            print(f"launch: workers failed (rc={rc}) after "
                  f"{attempt + 1} attempt(s); logs in {args.log_dir}/",
                  file=sys.stderr)
            return rc
        attempt += 1
        print(f"launch: worker failure (rc={rc}); elastic restart "
              f"{attempt}/{args.max_restarts}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(launch())
