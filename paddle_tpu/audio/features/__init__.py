"""paddle.audio.features analog (reference: python/paddle/audio/features/
layers.py — Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC).

TPU-native: each feature is a Layer whose forward is stft -> power ->
(fbank matmul) -> (log/DCT), all jnp under dispatch, so a whole feature
pipeline jit-compiles into one XLA program with the matmuls on the MXU."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import apply_op, unwrap
from ...nn.layer.layers import Layer
from ...signal import stft
from ..functional import (get_window, compute_fbank_matrix, power_to_db,
                          create_dct)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """|STFT|^power (reference: features/layers.py Spectrogram)."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft, self.power = n_fft, power
        self.hop_length = hop_length or (win_length or n_fft) // 4
        self.win_length = win_length or n_fft
        self.center, self.pad_mode = center, pad_mode
        self.register_buffer(
            "window", Tensor(unwrap(get_window(window, self.win_length,
                                               fftbins=True)).astype(dtype)))

    def forward(self, x):
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    self.window, center=self.center, pad_mode=self.pad_mode)
        p = self.power

        def f(c):
            mag = jnp.abs(c)
            return mag if p == 1.0 else mag ** p
        return apply_op("spectrogram_power", f, spec)


class MelSpectrogram(Layer):
    """Spectrogram -> mel filterbank (reference: MelSpectrogram)."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length, window,
                                        power, center, pad_mode, dtype)
        self.register_buffer(
            "fbank_matrix",
            compute_fbank_matrix(sr, n_fft, n_mels, f_min, f_max, htk, norm,
                                 dtype))

    def forward(self, x):
        spec = self._spectrogram(x)

        def f(s, fb):
            return jnp.einsum("mf,...ft->...mt", fb.astype(s.dtype), s)
        return apply_op("mel_fbank", f, spec, self.fbank_matrix)


class LogMelSpectrogram(Layer):
    """MelSpectrogram in dB (reference: LogMelSpectrogram)."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value, self.amin, self.top_db = ref_value, amin, top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        ref, amin, top = self.ref_value, self.amin, self.top_db

        def f(m):
            return unwrap(power_to_db(m, ref, amin, top))
        return apply_op("log_mel", f, mel)


class MFCC(Layer):
    """LogMel -> DCT-II cepstral coefficients (reference: MFCC)."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError("n_mfcc cannot be larger than n_mels")
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer("dct_matrix", create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        logmel = self._log_melspectrogram(x)

        def f(m, d):
            return jnp.einsum("mk,...mt->...kt", d.astype(m.dtype), m)
        return apply_op("mfcc_dct", f, logmel, self.dct_matrix)
