"""paddle.audio.functional analog (reference: python/paddle/audio/functional/
functional.py + window.py).

TPU-native: everything is jnp math producing framework Tensors; fbank/DCT
matrices are built once on host (tiny) and the per-batch feature pipeline
(stft -> |.|^2 -> fbank matmul -> log) fuses under jit onto the MXU."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.dispatch import unwrap

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "power_to_db", "create_dct", "get_window"]


def _arr(x):
    return unwrap(x) if isinstance(x, Tensor) else x


def hz_to_mel(freq, htk=False):
    """reference: functional.py:29."""
    f = _arr(freq)
    if htk:
        out = 2595.0 * jnp.log10(1.0 + jnp.asarray(f, jnp.float32) / 700.0)
        return Tensor(out) if isinstance(freq, Tensor) else float(out)
    f = jnp.asarray(f, jnp.float32)
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (f - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    mels = jnp.where(f >= min_log_hz,
                     min_log_mel + jnp.log(jnp.maximum(f, 1e-10)
                                           / min_log_hz) / logstep, mels)
    return Tensor(mels) if isinstance(freq, Tensor) else float(mels)


def mel_to_hz(mel, htk=False):
    """reference: functional.py:83."""
    m = jnp.asarray(_arr(mel), jnp.float32)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
        return Tensor(out) if isinstance(mel, Tensor) else float(out)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * m
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    freqs = jnp.where(m >= min_log_mel,
                      min_log_hz * jnp.exp(logstep * (m - min_log_mel)),
                      freqs)
    return Tensor(freqs) if isinstance(mel, Tensor) else float(freqs)


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    """reference: functional.py:126."""
    lo = hz_to_mel(f_min, htk)
    hi = hz_to_mel(f_max, htk)
    mels = jnp.linspace(lo, hi, n_mels)
    return Tensor(unwrap(mel_to_hz(Tensor(mels), htk)).astype(dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    """reference: functional.py:166."""
    return Tensor(jnp.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """Triangular mel filterbank [n_mels, 1 + n_fft//2]
    (reference: functional.py:189)."""
    if f_max is None:
        f_max = sr / 2.0
    fftfreqs = unwrap(fft_frequencies(sr, n_fft))
    melfreqs = unwrap(mel_frequencies(n_mels + 2, f_min, f_max, htk))
    fdiff = jnp.diff(melfreqs)
    ramps = melfreqs[:, None] - fftfreqs[None, :]   # [n_mels+2, n_bins]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (melfreqs[2:n_mels + 2] - melfreqs[:n_mels])
        weights = weights * enorm[:, None]
    return Tensor(weights.astype(dtype))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """10*log10(S/ref), clipped at top_db below the peak
    (reference: functional.py:262)."""
    s = jnp.asarray(_arr(spect), jnp.float32)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return Tensor(log_spec) if isinstance(spect, Tensor) else log_spec


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """DCT-II matrix [n_mels, n_mfcc] (reference: functional.py:306)."""
    n = jnp.arange(n_mels, dtype=jnp.float32)
    k = jnp.arange(n_mfcc, dtype=jnp.float32)[None, :]
    dct = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k) * 2.0
    if norm == "ortho":
        dct = dct.at[:, 0].multiply(math.sqrt(1.0 / (4 * n_mels)))
        dct = dct.at[:, 1:].multiply(math.sqrt(1.0 / (2 * n_mels)))
    else:
        pass
    return Tensor(dct.astype(dtype))


# ---- windows (reference: window.py get_window) -------------------------------
def _extend(M, sym):
    return (M + 1, True) if not sym else (M, False)


def _truncate(w, trunc):
    return w[:-1] if trunc else w


def _window(name, M, sym, **kw):
    M1, trunc = _extend(M, sym)
    n = np.arange(M1)
    if M1 == 1:
        return np.ones(1)
    if name == "hann":
        w = 0.5 - 0.5 * np.cos(2 * np.pi * n / (M1 - 1))
    elif name == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * n / (M1 - 1))
    elif name == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * n / (M1 - 1))
             + 0.08 * np.cos(4 * np.pi * n / (M1 - 1)))
    elif name == "bartlett":
        w = 1.0 - np.abs(2 * n / (M1 - 1) - 1)
    elif name == "bohman":
        x = np.abs(2 * n / (M1 - 1) - 1)
        w = (1 - x) * np.cos(np.pi * x) + np.sin(np.pi * x) / np.pi
        w[0] = w[-1] = 0
    elif name == "nuttall":
        a = [0.3635819, 0.4891775, 0.1365995, 0.0106411]
        fac = 2 * np.pi * n / (M1 - 1)
        w = (a[0] - a[1] * np.cos(fac) + a[2] * np.cos(2 * fac)
             - a[3] * np.cos(3 * fac))
    elif name == "kaiser":
        beta = kw.get("beta", 12.0)
        w = np.i0(beta * np.sqrt(1 - (2 * n / (M1 - 1) - 1) ** 2)) / \
            np.i0(beta)
    elif name == "gaussian":
        std = kw.get("std", 7.0)
        w = np.exp(-0.5 * ((n - (M1 - 1) / 2) / std) ** 2)
    elif name == "general_gaussian":
        p, sig = kw.get("p", 1.5), kw.get("sig", 7.0)
        w = np.exp(-0.5 * np.abs((n - (M1 - 1) / 2) / sig) ** (2 * p))
    elif name == "exponential":
        tau = kw.get("tau", 1.0)
        w = np.exp(-np.abs(n - (M1 - 1) / 2) / tau)
    elif name == "triang":
        m = (M1 + 1) // 2
        up = np.arange(1, m + 1)
        if M1 % 2 == 0:
            ww = (2 * up - 1.0) / M1
            w = np.concatenate([ww, ww[::-1]])
        else:
            ww = 2 * up / (M1 + 1.0)
            w = np.concatenate([ww, ww[-2::-1]])
    elif name == "tukey":
        alpha = kw.get("alpha", 0.5)
        if alpha <= 0:
            w = np.ones(M1)
        elif alpha >= 1:
            w = 0.5 - 0.5 * np.cos(2 * np.pi * n / (M1 - 1))
        else:
            width = int(alpha * (M1 - 1) / 2)
            w = np.ones(M1)
            edge = n[:width + 1]
            w[:width + 1] = 0.5 * (
                1 + np.cos(np.pi * (-1 + 2.0 * edge / alpha / (M1 - 1))))
            w[-(width + 1):] = w[:width + 1][::-1]
    else:
        raise ValueError(f"unknown window {name!r}")
    return _truncate(w, trunc)


def get_window(window, win_length, fftbins=True, dtype="float64"):
    """reference: window.py get_window — name or (name, param) tuple."""
    sym = not fftbins
    if isinstance(window, (list, tuple)):
        name, args = window[0], window[1:]
        param = {"kaiser": "beta", "gaussian": "std", "exponential": "tau",
                 "tukey": "alpha"}.get(name)
        kw = {param: args[0]} if (param and args) else {}
        if name == "general_gaussian" and len(args) >= 2:
            kw = {"p": args[0], "sig": args[1]}
        w = _window(name, win_length, sym, **kw)
    else:
        w = _window(window, win_length, sym)
    return Tensor(jnp.asarray(w.astype(dtype)))
