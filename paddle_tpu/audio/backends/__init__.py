"""paddle.audio.backends analog (reference: python/paddle/audio/backends —
wave_backend.py default, soundfile when installed).

Dependency-free WAV I/O via the stdlib `wave` module (the reference's
default backend does exactly this); soundfile is used when available."""
from __future__ import annotations

import wave as _wave

import numpy as np

from ...core.tensor import Tensor
from ...core.dispatch import unwrap

__all__ = ["list_available_backends", "get_current_backend", "set_backend",
           "load", "save", "info"]

_BACKEND = "wave_backend"


def list_available_backends():
    out = ["wave_backend"]
    try:
        import soundfile  # noqa: F401
        out.append("soundfile")
    except ImportError:
        pass
    return out


def get_current_backend():
    return _BACKEND


def set_backend(backend_name):
    global _BACKEND
    if backend_name not in list_available_backends():
        raise ValueError(f"backend {backend_name!r} not available "
                         f"(have {list_available_backends()})")
    _BACKEND = backend_name


class AudioInfo:
    def __init__(self, sample_rate, num_samples, num_channels,
                 bits_per_sample, encoding):
        self.sample_rate = sample_rate
        self.num_samples = num_samples
        self.num_channels = num_channels
        self.bits_per_sample = bits_per_sample
        self.encoding = encoding


def info(filepath):
    """reference: wave_backend.py info."""
    with _wave.open(filepath, "rb") as f:
        return AudioInfo(f.getframerate(), f.getnframes(), f.getnchannels(),
                         f.getsampwidth() * 8, "PCM_S")


def load(filepath, frame_offset=0, num_frames=-1, normalize=True,
         channels_first=True):
    """WAV -> (Tensor [C, N] or [N, C], sample_rate)
    (reference: wave_backend.py load)."""
    with _wave.open(filepath, "rb") as f:
        sr, ch, width = f.getframerate(), f.getnchannels(), f.getsampwidth()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
    dt = {1: np.uint8, 2: np.int16, 4: np.int32}[width]
    a = np.frombuffer(raw, dtype=dt).reshape(-1, ch)
    if width == 1:
        a = a.astype(np.float32) / 128.0 - 1.0 if normalize else a
    elif normalize:
        a = a.astype(np.float32) / float(2 ** (8 * width - 1))
    out = a.T if channels_first else a
    import jax.numpy as jnp
    return Tensor(jnp.asarray(out)), sr


def save(filepath, src, sample_rate, channels_first=True,
         encoding="PCM_16", bits_per_sample=16):
    """Tensor -> PCM WAV at 8/16/32 bits (reference: wave_backend.py save)."""
    if bits_per_sample not in (8, 16, 32):
        raise ValueError(f"bits_per_sample must be 8/16/32, got "
                         f"{bits_per_sample}")
    a = np.asarray(unwrap(src) if isinstance(src, Tensor) else src)
    if channels_first:
        a = a.T
    store = {8: np.uint8, 16: np.int16, 32: np.int32}[bits_per_sample]
    if a.dtype.kind == "f":
        a = np.clip(a, -1.0, 1.0)
        if bits_per_sample == 8:          # WAV 8-bit is unsigned, midpoint 128
            a = ((a + 1.0) * 127.5).astype(store)
        else:
            a = (a * (2 ** (bits_per_sample - 1) - 1)).astype(store)
    else:
        a = a.astype(store)               # integer src: width conversion
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(a.shape[1] if a.ndim == 2 else 1)
        f.setsampwidth(bits_per_sample // 8)
        f.setframerate(int(sample_rate))
        f.writeframes(a.tobytes())
