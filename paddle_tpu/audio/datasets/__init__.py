"""paddle.audio.datasets analog (reference: python/paddle/audio/datasets —
TESS, ESC50; both download archives then index WAV files).

This environment has no egress, so datasets load from an existing local
`data_dir`; `download=True` without files raises with instructions (the
reference raises similarly when its download fails)."""
from __future__ import annotations

import os

from ...io import Dataset
from ..backends import load as _load

__all__ = ["TESS", "ESC50"]


class _FolderAudioDataset(Dataset):
    """Indexes <data_dir>/**/*.wav; label = class subfolder name."""

    def __init__(self, data_dir, mode="train", split_ratio=0.8,
                 feat_type="raw", archive_url="", **feat_kwargs):
        if data_dir is None or not os.path.isdir(data_dir):
            raise RuntimeError(
                f"{type(self).__name__}: dataset files not found at "
                f"{data_dir!r} and this environment cannot download "
                f"({archive_url}). Place the extracted archive there.")
        classes = sorted(d for d in os.listdir(data_dir)
                         if os.path.isdir(os.path.join(data_dir, d)))
        self.classes = classes
        # split WITHIN each class so train/test both cover every label
        self._files, self._labels = [], []
        for ci, c in enumerate(classes):
            fs = [os.path.join(data_dir, c, f)
                  for f in sorted(os.listdir(os.path.join(data_dir, c)))
                  if f.endswith(".wav")]
            cut = int(len(fs) * split_ratio)
            keep = fs[:cut] if mode == "train" else fs[cut:]
            self._files += keep
            self._labels += [ci] * len(keep)
        self._feat_type = feat_type
        self._feat_kwargs = feat_kwargs
        self._feat_cache = {}    # sr -> feature Layer (built once, reused)

    def __len__(self):
        return len(self._files)

    def _feature(self, sr):
        if sr not in self._feat_cache:
            from ..features import (MelSpectrogram, LogMelSpectrogram,
                                    Spectrogram, MFCC)
            cls = {"melspectrogram": MelSpectrogram,
                   "logmelspectrogram": LogMelSpectrogram,
                   "spectrogram": Spectrogram,
                   "mfcc": MFCC}[self._feat_type]
            kw = dict(self._feat_kwargs)
            if cls is not Spectrogram:   # Spectrogram is sr-independent
                kw.setdefault("sr", sr)
            self._feat_cache[sr] = cls(**kw)
        return self._feat_cache[sr]

    def __getitem__(self, idx):
        wav, sr = _load(self._files[idx])
        if self._feat_type == "raw":
            return wav, self._labels[idx]
        return self._feature(sr)(wav), self._labels[idx]


class TESS(_FolderAudioDataset):
    """Toronto Emotional Speech Set (reference: audio/datasets/tess.py)."""

    def __init__(self, mode="train", data_dir=None, feat_type="raw", **kw):
        super().__init__(data_dir, mode, 0.8, feat_type,
                         archive_url="TESS_Toronto_emotional_speech_set.zip",
                         **kw)


class ESC50(_FolderAudioDataset):
    """ESC-50 environmental sounds (reference: audio/datasets/esc50.py)."""

    def __init__(self, mode="train", data_dir=None, feat_type="raw", **kw):
        super().__init__(data_dir, mode, 0.8, feat_type,
                         archive_url="ESC-50-master.zip", **kw)
