"""paddle.audio analog (reference: python/paddle/audio — functional DSP,
feature Layers, WAV backends, datasets)."""
from . import functional
from . import features
from . import backends
from . import datasets
from .backends import load, save, info

__all__ = ["functional", "features", "backends", "datasets", "load", "save",
           "info"]
