"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's capabilities.

Built on JAX/XLA/PJRT (compute), GSPMD (parallelism), Pallas (custom kernels).
See SURVEY.md for the reference blueprint this implements.
"""
from __future__ import annotations

import importlib

# core types
from .core.tensor import Tensor, Parameter
from .core.dtype import (
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype,
)
from .core.device import (
    set_device, get_device, device_count, is_compiled_with_cuda,
    is_compiled_with_xpu, is_compiled_with_cinn, Place,
)
from .core.flags import set_flags, get_flags
from .core.rng import seed, get_rng_state, set_rng_state, Generator
from .core import enforce

# ops (flat namespace like paddle.*)
from .ops import *  # noqa: F401,F403
from .ops import creation as _creation

# autograd
from .autograd import no_grad, enable_grad, set_grad_enabled, is_grad_enabled, grad
from . import autograd

from .version import __version__

bool = bool_  # paddle.bool


def is_tensor(x):
    return isinstance(x, Tensor)


def tensor(data, dtype=None, place=None, stop_gradient=True):
    return _creation.to_tensor(data, dtype, place, stop_gradient)


def in_dynamic_mode():
    from .core.dispatch import _state
    return _state.trace_ctx is None


def in_dynamic_or_pir_mode():
    return True


def enable_static():  # static mode is to_static-based; kept for API compat
    pass


def disable_static():
    pass


def disable_signal_handler():
    pass


# Subpackages load lazily (PEP 562) so `import paddle_tpu` stays light and the
# core never depends on higher layers.
_LAZY = {
    "nn", "optimizer", "amp", "io", "jit", "distributed", "static", "framework",
    "device", "profiler", "metric", "vision", "incubate", "sparse",
    "distribution", "hapi", "utils", "models", "parallel", "text", "audio",
    "quantization", "onnx", "inference", "geometric", "signal", "fft",
    "strings", "observability",
}

_LAZY_ATTRS = {
    "save": ("paddle_tpu.framework.io", "save"),
    "load": ("paddle_tpu.framework.io", "load"),
    "DataParallel": ("paddle_tpu.distributed.parallel", "DataParallel"),
    "Model": ("paddle_tpu.hapi.model", "Model"),
    "summary": ("paddle_tpu.hapi.model", "summary"),
    "flops": ("paddle_tpu.hapi.model", "flops"),
    "linalg": ("paddle_tpu.ops", "linalg"),
    "CPUPlace": ("paddle_tpu.core.device", "Place"),
    "get_default_generator": ("paddle_tpu.core.rng", "default_generator"),
}


def __getattr__(name):
    if name in _LAZY:
        mod = importlib.import_module(f"paddle_tpu.{name}")
        globals()[name] = mod
        return mod
    if name in _LAZY_ATTRS:
        modname, attr = _LAZY_ATTRS[name]
        val = getattr(importlib.import_module(modname), attr)
        globals()[name] = val
        return val
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")

# ---- top-level compat surface (reference python/paddle/__init__.py) ---------
import math as _math

inf = float("inf")
nan = float("nan")
pi = _math.pi
e = _math.e
newaxis = None

from .framework.compat import (  # noqa: F401,E402
    dtype, iinfo, finfo, set_printoptions, CUDAPlace, CUDAPinnedPlace,
    get_cuda_rng_state, set_cuda_rng_state, to_dlpack, from_dlpack,
    LazyGuard, batch, check_shape, pstring, raw)
from .nn.initializer import ParamAttr  # noqa: F401,E402
