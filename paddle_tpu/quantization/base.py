"""Quantization bases (reference: python/paddle/quantization/base_observer.py,
base_quanter.py, factory.py).

TPU-native: fake-quant is ONE jit-friendly op with a custom straight-through
vjp (jax.custom_vjp) dispatched like every other op, so QAT graphs capture
into a single XLA program under to_static; observers keep their running
statistics in Layer buffers (capture-lifted like RNG state)."""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ..nn.layer.layers import Layer

__all__ = ["BaseObserver", "BaseQuanter", "ObserverFactory", "QuanterFactory",
           "quanter", "fake_quant"]


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def fake_quant(x, scale, qmax):
    """Symmetric fake quantize-dequantize: round(x/s*qmax)/qmax*s, clipped.

    scale broadcasts against x (scalar for per-tensor, shaped for
    per-channel). The vjp is the clipped straight-through estimator
    (reference: fake_quantize_dequantize_moving_average_abs_max grad)."""
    s = jnp.maximum(scale, 1e-9).astype(x.dtype)
    return jnp.clip(jnp.round(x / s * qmax), -qmax, qmax) / qmax * s


def _fq_fwd(x, scale, qmax):
    return fake_quant(x, scale, qmax), (x, scale)


def _fq_bwd(qmax, res, g):
    x, scale = res
    s = jnp.maximum(scale, 1e-9).astype(x.dtype)
    mask = (jnp.abs(x) <= s).astype(g.dtype)
    # no gradient to the observer-updated scale
    return g * mask, jnp.zeros(jnp.shape(scale), dtype=g.dtype)


fake_quant.defvjp(_fq_fwd, _fq_bwd)


class BaseObserver(Layer):
    """Collects statistics on the tensors flowing through it; identity in
    the forward graph (reference: base_observer.py BaseObserver)."""

    def bit_length(self):
        return 8

    def quant_axis(self):
        return -1

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None

    def cal_thresholds(self):
        pass


class BaseQuanter(BaseObserver):
    """Applies fake quantization in forward (reference: base_quanter.py)."""


class _Factory:
    """Holds a quanter/observer class + kwargs; instantiated per wrapped
    layer (reference: factory.py ObserverFactory/QuanterFactory)."""

    def __init__(self, cls=None, **kwargs):
        self._cls = cls or self._get_class()
        self._kwargs = kwargs

    def _get_class(self):
        raise NotImplementedError

    def _instance(self, layer):
        return self._cls(layer, **self._kwargs)


class ObserverFactory(_Factory):
    pass


class QuanterFactory(_Factory):
    pass


def quanter(name):
    """Class decorator registering a quanter layer under a factory name
    (reference: factory.py quanter). Returns the class unchanged and
    exposes `<name>` as a factory in the class's module."""
    def deco(cls):
        import sys
        mod = sys.modules[cls.__module__]

        class _F(QuanterFactory):
            def _get_class(self):
                return cls
        _F.__name__ = name
        setattr(mod, name, _F)
        return cls
    return deco
