"""QuantConfig (reference: python/paddle/quantization/config.py:67).

Maps layers (by instance, type, or name) to activation/weight quanter
factories and declares which layer types have quantized (QAT) counterparts."""
from __future__ import annotations

from ..nn.layer.layers import Layer

__all__ = ["QuantConfig"]


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global_act, self._global_wt = activation, weight
        self._layer_cfg = {}       # id(layer) -> (act, wt)
        self._type_cfg = {}        # type -> (act, wt)
        self._name_cfg = {}        # layer name -> (act, wt)
        self._qat_mapping = {}     # source type -> quanted type
        self._customized_leaves = []

    # -- registration (reference config.py add_layer_config etc.) ------------
    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._layer_cfg[id(l)] = (activation, weight)

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._type_cfg[t] = (activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) \
            else [layer_name]
        for n in names:
            self._name_cfg[n] = (activation, weight)

    def add_qat_layer_mapping(self, source, target):
        self._qat_mapping[source] = target

    def add_customized_leaves(self, layer_type):
        self._customized_leaves.append(layer_type)

    @property
    def customized_leaves(self):
        return self._customized_leaves

    # -- lookup ---------------------------------------------------------------
    def _get_config_by_layer(self, layer, name=None):
        if id(layer) in self._layer_cfg:
            return self._layer_cfg[id(layer)]
        if name is not None and name in self._name_cfg:
            return self._name_cfg[name]
        for t, cfg in self._type_cfg.items():
            if isinstance(layer, t):
                return cfg
        return (self._global_act, self._global_wt)

    def _is_quantifiable(self, layer, name=None):
        act, wt = self._get_config_by_layer(layer, name)
        return act is not None or wt is not None

    def quanted_type_of(self, layer):
        from .qat_layers import default_qat_mapping
        mapping = default_qat_mapping()
        mapping.update(self._qat_mapping)
        for src, dst in mapping.items():
            if type(layer) is src:
                return dst
        return None

    def __str__(self):
        return (f"QuantConfig(global_act={self._global_act}, "
                f"global_wt={self._global_wt}, "
                f"types={list(self._type_cfg)})")
