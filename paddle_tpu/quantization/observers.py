"""Observers: collect activation/weight ranges for PTQ (reference:
python/paddle/quantization/observers/abs_max.py AbsmaxObserverLayer,
imperative/ptq_quantizer.py AbsmaxQuantizer/PerChannelAbsmaxQuantizer/
HistQuantizer/KLQuantizer).

TPU-native: running stats live in jnp scalars updated eagerly (observation is
a calibration-time, host-driven pass — it never needs to be in the compiled
training graph)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import unwrap
from .base import BaseObserver, ObserverFactory

__all__ = ["AbsmaxObserver", "AbsmaxObserverLayer", "PerChannelAbsmaxObserver",
           "PerChannelAbsmaxObserverLayer", "HistObserver",
           "HistObserverLayer", "KLObserver", "KLObserverLayer"]


class AbsmaxObserverLayer(BaseObserver):
    """Running max-of-|x| (reference: observers/abs_max.py:48)."""

    def __init__(self, layer=None, quant_bits=8):
        super().__init__()
        self._bits = quant_bits
        self._max = 0.0

    def forward(self, x):
        self._max = max(self._max,
                        float(jnp.max(jnp.abs(unwrap(x)))))
        return x

    def bit_length(self):
        return self._bits

    def scales(self):
        return Tensor(jnp.asarray(self._max, jnp.float32))


class PerChannelAbsmaxObserverLayer(BaseObserver):
    """Per-output-channel |x| max (reference: PerChannelAbsmaxQuantizer)."""

    def __init__(self, layer=None, quant_bits=8, quant_axis=0):
        super().__init__()
        self._bits = quant_bits
        self._axis = quant_axis
        self._max = None

    def forward(self, x):
        a = jnp.abs(unwrap(x))
        axes = tuple(i for i in range(a.ndim) if i != self._axis % a.ndim)
        m = jnp.max(a, axis=axes)
        self._max = m if self._max is None else jnp.maximum(self._max, m)
        return x

    def bit_length(self):
        return self._bits

    def quant_axis(self):
        return self._axis

    def scales(self):
        return Tensor(jnp.asarray(self._max, jnp.float32))


class HistObserverLayer(BaseObserver):
    """Histogram percentile threshold (reference: HistQuantizer).

    Keeps a FIXED-size running histogram of |x| (re-binned when the range
    grows) so calibration memory is O(bins), not O(total activations)."""

    def __init__(self, layer=None, quant_bits=8, bins=2048,
                 percentile=0.9999):
        super().__init__()
        self._bits = quant_bits
        self._bins = bins
        self._pct = percentile
        self._hist = None
        self._maxv = 0.0

    def forward(self, x):
        a = np.abs(np.asarray(unwrap(x))).ravel()
        if a.size == 0:
            return x
        bmax = float(a.max())
        if self._hist is None:
            self._maxv = max(bmax, 1e-12)
            self._hist = np.histogram(
                a, bins=self._bins, range=(0, self._maxv))[0].astype(
                    np.float64)
            return x
        if bmax > self._maxv:
            # redistribute existing mass into the wider range via the CDF
            old_edges = np.linspace(0, self._maxv, self._bins + 1)
            new_edges = np.linspace(0, bmax, self._bins + 1)
            cum = np.concatenate([[0.0], np.cumsum(self._hist)])
            self._hist = np.diff(np.interp(new_edges, old_edges, cum))
            self._maxv = bmax
        self._hist += np.histogram(a, bins=self._bins,
                                   range=(0, self._maxv))[0]
        return x

    def bit_length(self):
        return self._bits

    def cal_thresholds(self):
        pass

    def _edges(self):
        return np.linspace(0, self._maxv, self._bins + 1)

    def scales(self):
        if self._hist is None:
            return Tensor(jnp.asarray(0.0, jnp.float32))
        cdf = np.cumsum(self._hist) / max(self._hist.sum(), 1)
        idx = int(np.searchsorted(cdf, self._pct))
        return Tensor(jnp.asarray(self._edges()[min(idx + 1, self._bins)],
                                  jnp.float32))


class KLObserverLayer(HistObserverLayer):
    """KL-minimizing threshold (reference: KLQuantizer — TensorRT-style
    sweep over candidate clip points, pick min KL(P||Q))."""

    def scales(self):
        if self._hist is None:
            return Tensor(jnp.asarray(0.0, jnp.float32))
        hist, edges = self._hist.astype(np.float64), self._edges()
        nlevels = 2 ** (self._bits - 1)
        best_kl, best_i = np.inf, self._bins
        for i in range(nlevels, self._bins + 1, max(1, self._bins // 64)):
            p = hist[:i].copy()
            p[-1] += hist[i:].sum()  # clip mass into the last bin
            if p.sum() == 0:
                continue
            # quantize the i-bin histogram down to nlevels buckets
            factor = i / nlevels
            q = np.zeros(i)
            for b in range(nlevels):
                lo, hi = int(b * factor), int((b + 1) * factor)
                seg = hist[lo:hi]
                nz = (seg > 0).sum()
                if nz:
                    q[lo:hi] = np.where(seg > 0, seg.sum() / nz, 0)
            pn, qn = p / p.sum(), q / max(q.sum(), 1e-12)
            m = (pn > 0) & (qn > 0)
            kl = float((pn[m] * np.log(pn[m] / qn[m])).sum())
            if kl < best_kl:
                best_kl, best_i = kl, i
        return Tensor(jnp.asarray(edges[best_i], jnp.float32))


class AbsmaxObserver(ObserverFactory):
    def _get_class(self):
        return AbsmaxObserverLayer


class PerChannelAbsmaxObserver(ObserverFactory):
    def _get_class(self):
        return PerChannelAbsmaxObserverLayer


class HistObserver(ObserverFactory):
    def _get_class(self):
        return HistObserverLayer


class KLObserver(ObserverFactory):
    def _get_class(self):
        return KLObserverLayer
