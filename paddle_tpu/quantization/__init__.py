"""paddle.quantization analog (reference: python/paddle/quantization — 3.9k
LoC: QuantConfig/QAT/PTQ + observers + quanters + imperative pass).

TPU-native: QAT fake-quant is one custom-vjp op (STE) that captures into a
single XLA program under to_static; PTQ freezes to int8-weight layers whose
dequant folds into the MXU matmul epilogue (weight-only int8/int4 — the
bandwidth-bound decode case the TPU actually cares about)."""
from .base import (BaseObserver, BaseQuanter, ObserverFactory, QuanterFactory,
                   quanter, fake_quant)
from .config import QuantConfig
from .observers import (AbsmaxObserver, AbsmaxObserverLayer,
                        PerChannelAbsmaxObserver,
                        PerChannelAbsmaxObserverLayer, HistObserver,
                        HistObserverLayer, KLObserver, KLObserverLayer)
from .quanters import (FakeQuanterWithAbsMaxObserver,
                       FakeQuanterWithAbsMaxObserverLayer,
                       FakeQuanterChannelWiseAbsMax,
                       FakeQuanterChannelWiseAbsMaxLayer)
from .qat_layers import (QuantedLinear, QuantedConv2D, QuantizedLinearInfer,
                         QuantizedConv2DInfer)
from .quantize import Quantization, QAT, PTQ, ObserveWrapper
from .weight_only import (weight_quantize, weight_dequantize,
                          weight_only_linear)

# imperative-API aliases (reference: quantization/imperative/ptq_quantizer.py)
AbsmaxQuantizer = AbsmaxObserver
PerChannelAbsmaxQuantizer = PerChannelAbsmaxObserver
HistQuantizer = HistObserver
KLQuantizer = KLObserver

__all__ = [
    "BaseObserver", "BaseQuanter", "ObserverFactory", "QuanterFactory",
    "quanter", "fake_quant", "QuantConfig", "AbsmaxObserver",
    "AbsmaxObserverLayer", "PerChannelAbsmaxObserver",
    "PerChannelAbsmaxObserverLayer", "HistObserver", "HistObserverLayer",
    "KLObserver", "KLObserverLayer", "FakeQuanterWithAbsMaxObserver",
    "FakeQuanterWithAbsMaxObserverLayer", "FakeQuanterChannelWiseAbsMax",
    "FakeQuanterChannelWiseAbsMaxLayer", "QuantedLinear", "QuantedConv2D",
    "QuantizedLinearInfer", "QuantizedConv2DInfer", "Quantization", "QAT",
    "PTQ", "ObserveWrapper",
    "weight_quantize", "weight_dequantize", "weight_only_linear",
    "AbsmaxQuantizer", "PerChannelAbsmaxQuantizer", "HistQuantizer",
    "KLQuantizer",
]
