"""Weight-only quantization for LLM serving (reference:
python/paddle/nn/quant/quantized_linear.py weight_quantize /
weight_only_linear over phi weight_only_linear_kernel).

TPU-native: int8 weights live in HBM at half/quarter the bytes; the matmul
upcasts in-register and applies the per-channel scale in the epilogue —
XLA fuses `(x @ int8.astype(bf16)) * scale` into one MXU op, halving the
weight-streaming bandwidth that dominates decode."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear"]


def weight_quantize(x, algo="weight_only_int8", group_size=-1):
    """w [in, out] -> (qw int8 [in, out] or packed int4, scale f32 [out]).

    algo: weight_only_int8 | weight_only_int4 (packed two nibbles/byte)."""
    w = unwrap(x)
    if algo not in ("weight_only_int8", "weight_only_int4"):
        raise ValueError(f"unsupported algo {algo}")
    bits = 8 if algo.endswith("int8") else 4
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(jnp.max(jnp.abs(w), axis=0) / qmax, 1e-9)
    q = jnp.clip(jnp.round(w / s[None, :]), -qmax, qmax).astype(jnp.int8)
    if bits == 4:
        if q.shape[0] % 2:
            raise ValueError("int4 packing needs even in_features")
        lo = q[0::2] & 0xF
        hi = (q[1::2] & 0xF) << 4
        q = (lo | hi).astype(jnp.int8)          # [in//2, out]
    return Tensor(q), Tensor(s.astype(jnp.float32))


def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float32"):
    q, s = unwrap(x), unwrap(scale)
    if algo.endswith("int4"):
        lo = (q << 4).astype(jnp.int8) >> 4     # sign-extend low nibble
        hi = q >> 4                              # arithmetic shift: high
        q = jnp.stack([lo, hi], axis=1).reshape(-1, q.shape[-1])
    return Tensor((q.astype(jnp.float32) * s[None, :]).astype(out_dtype))


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias (reference: quantized_linear.py:33).

    On TPU, no-grad calls with block-divisible shapes run the Pallas
    quant-matmul kernel: int8/int4 tiles dequantize in VMEM and feed the
    MXU directly, so the bf16 weight copy is NEVER materialized in HBM —
    the weight stream (what bounds decode) stays at the quantized width.
    Other cases use the XLA dequant formulation."""
    is4 = str(weight_dtype) == "int4"
    import jax
    from ..core.dispatch import _requires_grad
    from ..ops.pallas import quant_matmul as qmm
    K_in = (unwrap(weight).shape[0] * (2 if is4 else 1))
    N = unwrap(weight).shape[1]
    xa = unwrap(x)
    M = int(np.prod(xa.shape[:-1])) if xa.ndim > 1 else 1
    from ..core import flags as _flags
    use_kernel = (_flags.flag("weight_only_use_kernel")
                  and jax.default_backend() in ("tpu", "axon")
                  and not _requires_grad((x, weight, weight_scale))
                  and xa.shape[-1] == K_in
                  and qmm.supported(M, K_in, N, int4=is4))

    def f(a, qw, s, *b):
        lead = a.shape[:-1]
        if use_kernel:
            y2 = qmm.quant_matmul(a.reshape(-1, a.shape[-1]), qw,
                                  s.astype(jnp.float32), int4=is4)
            y = y2.reshape(*lead, qw.shape[-1])
        else:
            if is4:
                lo = (qw << 4).astype(jnp.int8) >> 4
                hi = qw >> 4
                wq = jnp.stack([lo, hi], axis=1).reshape(-1, qw.shape[-1])
            else:
                wq = qw
            y = (a @ wq.astype(a.dtype)) * s.astype(a.dtype)
        return y + b[0].astype(a.dtype) if b else y

    args = (x, weight, weight_scale) + ((bias,) if bias is not None else ())
    return apply_op("weight_only_linear", f, *args)
