"""Trainable fake quanters for QAT (reference:
python/paddle/quantization/quanters/abs_max.py
FakeQuanterWithAbsMaxObserverLayer).

TPU-native: the EMA scale is a Layer buffer (a Tensor), so when a QAT train
step is captured by to_static the scale update is lifted into the compiled
program as a mutated input — the whole QAT step stays ONE XLA program."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from .base import BaseQuanter, QuanterFactory, fake_quant

__all__ = ["FakeQuanterWithAbsMaxObserver",
           "FakeQuanterWithAbsMaxObserverLayer",
           "FakeQuanterChannelWiseAbsMax",
           "FakeQuanterChannelWiseAbsMaxLayer"]


class FakeQuanterWithAbsMaxObserverLayer(BaseQuanter):
    """Moving-average absmax scale + fake quant with STE
    (reference: quanters/abs_max.py:96 dynamic_forward)."""

    def __init__(self, layer=None, moving_rate=0.9, bit_length=8, dtype=None,
                 name=None):
        super().__init__()
        self._rate = moving_rate
        self._bits = bit_length
        self._qmax = float(2 ** (bit_length - 1) - 1)
        self.register_buffer("scale", Tensor(jnp.asarray(0.0, jnp.float32)))
        self.register_buffer("state", Tensor(jnp.asarray(0.0, jnp.float32)))

    def forward(self, x):
        if self.training:
            def upd(a, sc, st):
                absmax = jnp.max(jnp.abs(a)).astype(jnp.float32)
                st2 = st * self._rate + 1.0
                sc2 = (sc * self._rate * st + absmax) / st2
                return sc2, st2
            sc2, st2 = apply_op("fq_absmax_update", upd, x, self.scale,
                                self.state)
            self.scale._data = unwrap(sc2)
            self.state._data = unwrap(st2)
        qmax = self._qmax

        def fq(a, s):
            return fake_quant(a, s, qmax)
        return apply_op("fake_quant_absmax", fq, x, self.scale)

    def bit_length(self):
        return self._bits

    def scales(self):
        return self.scale


class FakeQuanterChannelWiseAbsMaxLayer(BaseQuanter):
    """Per-channel absmax fake quanter for weights (reference:
    quanters/abs_max.py channel-wise path; quant_axis = output channel)."""

    def __init__(self, layer=None, bit_length=8, quant_axis=-1, dtype=None,
                 name=None):
        super().__init__()
        self._bits = bit_length
        self._axis = quant_axis
        self._qmax = float(2 ** (bit_length - 1) - 1)

    def forward(self, x):
        axis = self._axis % x.ndim
        qmax = self._qmax

        def fq(a):
            axes = tuple(i for i in range(a.ndim) if i != axis)
            s = jnp.max(jnp.abs(a), axis=axes, keepdims=True)
            shape = [1] * a.ndim
            shape[axis] = a.shape[axis]
            return fake_quant(a, s.reshape(shape), qmax)
        return apply_op("fake_quant_channel", fq, x)

    def bit_length(self):
        return self._bits

    def quant_axis(self):
        return self._axis

    def scales(self, x):
        """Scale is a pure function of the quantized tensor (per-channel
        absmax), so it's derived on demand from a concrete tensor rather
        than cached in forward — caching there would leak tracers when the
        forward runs under to_static capture."""
        a = jnp.abs(unwrap(x))
        axis = self._axis % a.ndim
        axes = tuple(i for i in range(a.ndim) if i != axis)
        return Tensor(jnp.max(a, axis=axes).astype(jnp.float32))


class FakeQuanterWithAbsMaxObserver(QuanterFactory):
    def _get_class(self):
        return FakeQuanterWithAbsMaxObserverLayer


class FakeQuanterChannelWiseAbsMax(QuanterFactory):
    def _get_class(self):
        return FakeQuanterChannelWiseAbsMaxLayer
