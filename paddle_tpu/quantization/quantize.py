"""QAT / PTQ passes (reference: python/paddle/quantization/qat.py QAT,
ptq.py PTQ, quantize.py Quantization, wrapper.py ObserveWrapper).

Model surgery walks the Layer tree and swaps matched sublayers for their
quanted counterparts (QAT) or wraps them with observers (PTQ); `convert`
freezes to int8-weight inference layers."""
from __future__ import annotations

import copy

from ..nn.layer.layers import Layer
from .config import QuantConfig

__all__ = ["Quantization", "QAT", "PTQ", "ObserveWrapper"]


class ObserveWrapper(Layer):
    """Runs the observer on the sublayer's OUTPUT activations
    (reference: wrapper.py ObserveWrapper)."""

    def __init__(self, observer, observed, observe_input=True):
        super().__init__()
        self._observer = observer
        self._observed = observed
        self._observe_input = observe_input

    def forward(self, *args, **kwargs):
        if self._observe_input and args and self._observer is not None:
            args = (self._observer(args[0]),) + tuple(args[1:])
        out = self._observed(*args, **kwargs)
        if not self._observe_input and self._observer is not None:
            out = self._observer(out)
        return out


def _walk_replace(model, fn, prefix=""):
    """Depth-first: fn(name, sublayer) -> replacement or None."""
    for name, sub in list(model._sub_layers.items()):
        full = f"{prefix}.{name}" if prefix else name
        repl = fn(full, sub)
        if repl is not None:
            model._sub_layers[name] = repl
        else:
            _walk_replace(sub, fn, full)
    return model


class Quantization:
    """reference: quantize.py Quantization base."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model, inplace=False):
        raise NotImplementedError

    def convert(self, model, inplace=False, remain_weight=False):
        """Swap QAT/observed layers for frozen int8 inference layers."""
        target = model if inplace else copy.deepcopy(model)

        def fn(name, sub):
            if isinstance(sub, ObserveWrapper):
                inner = sub._observed
                conv = getattr(inner, "convert", None)
                return conv() if conv is not None else inner
            if hasattr(sub, "convert"):
                try:
                    return sub.convert()
                except NotImplementedError:
                    return None
            return None
        out = _walk_replace(target, fn)
        out.eval()
        return out

    def _details(self):
        return str(self._config)

    def __str__(self):
        return self._details()

    __repr__ = __str__


class QAT(Quantization):
    """Quantization-aware training pass (reference: qat.py:27)."""

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        target = model if inplace else copy.deepcopy(model)

        def fn(name, sub):
            qtype = self._config.quanted_type_of(sub)
            if qtype is not None and self._config._is_quantifiable(sub, name):
                return qtype(sub, self._config, name)
            return None
        return _walk_replace(target, fn)


class PTQ(Quantization):
    """Post-training quantization pass (reference: ptq.py:29): insert
    observers, calibrate by running data, then convert()."""

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        target = model if inplace else copy.deepcopy(model)

        def fn(name, sub):
            qtype = self._config.quanted_type_of(sub)
            if qtype is None or not self._config._is_quantifiable(sub, name):
                return None
            quanted = qtype(sub, self._config, name)
            # PTQ: weights observed once (they're fixed); activations
            # observed during calibration via the wrapper
            if quanted.weight_quanter is not None:
                quanted.weight_quanter.eval()
                quanted.weight_quanter(quanted.weight)
            obs = quanted.activation_quanter
            quanted.activation_quanter = None
            if obs is not None:
                obs.eval()
                return ObserveWrapper(obs, quanted, observe_input=True)
            return quanted
        target = _walk_replace(target, fn)
        target.eval()
        return target
