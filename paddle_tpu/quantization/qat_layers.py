"""Quantized counterpart layers used by QAT/PTQ convert (reference:
python/paddle/nn/quant/qat/linear.py QuantedLinear,
paddle/nn/quant/format.py ConvertibleQuantedLayer).

TPU-native: a quanted layer shares the SAME weight/bias Parameter objects as
the float layer it replaces (no copy), applies fake-quant ops around the
original math, and converts to an int8-weight inference layer whose matmul
dequantizes per output channel — XLA fuses the (int8 -> bf16 multiply-by-scale)
into the matmul epilogue on the MXU."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from ..nn.layer.layers import Layer
from ..nn import functional as F

__all__ = ["QuantedLinear", "QuantedConv2D", "QuantizedLinearInfer",
           "default_qat_mapping"]


def _make_quanters(config, layer, name=None):
    act_f, wt_f = config._get_config_by_layer(layer, name)
    act = act_f._instance(layer) if act_f is not None else None
    wt = wt_f._instance(layer) if wt_f is not None else None
    return act, wt


class QuantedLinear(Layer):
    """reference: nn/quant/qat/linear.py QuantedLinear."""

    def __init__(self, layer, q_config, name=None):
        super().__init__()
        self._float_layer = layer
        self.weight = layer.weight
        self.bias = layer.bias
        self.activation_quanter, self.weight_quanter = \
            _make_quanters(q_config, layer, name)

    def forward(self, x):
        # getattr: Layer.__setattr__(None) deletes a sublayer slot (PTQ
        # detaches the act quanter into an ObserveWrapper)
        aq = getattr(self, "activation_quanter", None)
        wq = getattr(self, "weight_quanter", None)
        if aq is not None:
            x = aq(x)
        w = self.weight if wq is None else wq(self.weight)
        return F.linear(x, w, self.bias)

    def weight_scales(self):
        wq = getattr(self, "weight_quanter", None)
        if wq is None:
            return None
        try:
            return wq.scales(self.weight)   # channel-wise: derive from weight
        except TypeError:
            return wq.scales()

    def convert(self):
        """-> int8-weight inference layer with fixed scales."""
        return QuantizedLinearInfer.from_float(
            self.weight, self.bias, self.weight_scales())


class QuantedConv2D(Layer):
    """reference: nn/quant/qat/conv.py QuantedConv2D."""

    def __init__(self, layer, q_config, name=None):
        super().__init__()
        self._float_layer = layer
        self.weight = layer.weight
        self.bias = layer.bias
        self.activation_quanter, self.weight_quanter = \
            _make_quanters(q_config, layer, name)

    def forward(self, x):
        aq = getattr(self, "activation_quanter", None)
        wq = getattr(self, "weight_quanter", None)
        if aq is not None:
            x = aq(x)
        w = self.weight if wq is None else wq(self.weight)
        l = self._float_layer
        return F.conv2d(x, w, self.bias, l._stride, l._padding, l._dilation,
                        l._groups, l._data_format)

    def convert(self):
        wq = getattr(self, "weight_quanter", None)
        scales = None
        if wq is not None:
            try:
                scales = wq.scales(self.weight)
            except TypeError:
                scales = wq.scales()
        return QuantizedConv2DInfer.from_float(self._float_layer, scales)


class QuantizedLinearInfer(Layer):
    """Inference layer: int8 weights + per-output-channel f32 scales.

    y = (x @ dequant(qw)) + b where dequant is a column-wise scale multiply;
    XLA folds the scale into the matmul epilogue (reference:
    phi weight_only_linear kernel)."""

    def __init__(self, qweight, scale, bias=None):
        super().__init__()
        self.register_buffer("qweight", Tensor(qweight))   # int8 [in, out]
        self.register_buffer("scale", Tensor(scale))       # f32 [out]
        self.bias = bias

    @staticmethod
    def from_float(weight, bias, scales=None, bits=8):
        w = unwrap(weight)
        qmax = float(2 ** (bits - 1) - 1)
        if scales is None:
            s = jnp.max(jnp.abs(w), axis=0) / qmax            # per out-col
        else:
            s = unwrap(scales) / qmax
            if s.ndim == 0:                                   # per-tensor
                s = jnp.full((w.shape[-1],), s)
            elif s.shape != (w.shape[-1],):
                raise ValueError(
                    f"per-channel scales must index the OUTPUT channel "
                    f"(expected shape ({w.shape[-1]},), got {s.shape}); "
                    f"for [in, out] Linear weights use quant_axis=1/-1")
        s = jnp.maximum(s, 1e-9)
        qw = jnp.clip(jnp.round(w / s[None, :]), -qmax, qmax).astype(jnp.int8)
        return QuantizedLinearInfer(qw, s.astype(jnp.float32), bias)

    def forward(self, x):
        def f(a, qw, s, *b):
            y = (a @ qw.astype(a.dtype)) * s.astype(a.dtype)
            return y + b[0].astype(a.dtype) if b else y
        args = (x, self.qweight, self.scale) + \
            ((self.bias,) if self.bias is not None else ())
        return apply_op("quantized_linear", f, *args)


class QuantizedConv2DInfer(Layer):
    """Inference conv: int8 weights [out, in, kh, kw] + per-out-channel f32
    scales; dequant is a per-channel multiply XLA fuses into the conv."""

    def __init__(self, qweight, scale, bias, conv_attrs):
        super().__init__()
        self.register_buffer("qweight", Tensor(qweight))
        self.register_buffer("scale", Tensor(scale))
        self.bias = bias
        self._attrs = conv_attrs

    @staticmethod
    def from_float(layer, scales=None, bits=8):
        w = unwrap(layer.weight)                 # [out, in, kh, kw]
        qmax = float(2 ** (bits - 1) - 1)
        if scales is None:
            s = jnp.max(jnp.abs(w), axis=(1, 2, 3)) / qmax
        else:
            s = unwrap(scales) / qmax
            if s.ndim == 0:
                s = jnp.full((w.shape[0],), s)
            elif s.shape != (w.shape[0],):
                raise ValueError(
                    f"conv per-channel scales must index the OUTPUT channel "
                    f"(expected shape ({w.shape[0]},), got {s.shape}); use "
                    f"quant_axis=0 for [out, in, kh, kw] conv weights")
        s = jnp.maximum(s, 1e-9)
        sb = s[:, None, None, None]
        qw = jnp.clip(jnp.round(w / sb), -qmax, qmax).astype(jnp.int8)
        attrs = dict(stride=layer._stride, padding=layer._padding,
                     dilation=layer._dilation, groups=layer._groups,
                     data_format=layer._data_format)
        return QuantizedConv2DInfer(qw, s.astype(jnp.float32), layer.bias,
                                    attrs)

    def forward(self, x):
        def dq(qw, s):
            return qw.astype(jnp.float32) * s[:, None, None, None]
        w = apply_op("conv_dequant", dq, self.qweight, self.scale)
        a = self._attrs
        return F.conv2d(x, w, self.bias, a["stride"], a["padding"],
                        a["dilation"], a["groups"], a["data_format"])


def default_qat_mapping():
    """Imported lazily so qat_layers doesn't circularly import nn at load."""
    from ..nn.layer.common import Linear
    from ..nn.layer.conv import Conv2D
    return {Linear: QuantedLinear, Conv2D: QuantedConv2D}
