"""paddle.distribution analog (reference: python/paddle/distribution — 9.3k LoC
over Distribution/ExponentialFamily bases + per-family modules + kl.py).

TPU-native: densities via jnp/jax.scipy.stats (fused by XLA), sampling via the
framework RNG (key-splitting Generator in core/rng.py, capture-safe). Every
method takes/returns framework Tensors and routes math through dispatch, so
log_prob is differentiable (reparameterized rsample where the family allows)."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from ..core.rng import next_key

__all__ = [
    "Distribution", "Normal", "Uniform", "Categorical", "Bernoulli", "Beta",
    "Gamma", "Exponential", "Laplace", "LogNormal", "Multinomial", "Poisson",
    "Geometric", "Cauchy", "Gumbel", "StudentT", "Dirichlet", "Binomial",
    "Chi2", "ContinuousBernoulli", "MultivariateNormal", "Independent",
    "TransformedDistribution", "kl_divergence", "register_kl",
]


def _t(x, dtype=jnp.float32):
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, dtype))


def _a(x):
    return unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x, jnp.float32)


def _shape(sample_shape, *params):
    batch = jnp.broadcast_shapes(*[jnp.shape(p) for p in params]) if params \
        else ()
    return tuple(sample_shape) + tuple(batch)


class Distribution:
    """reference: distribution/distribution.py Distribution base."""

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return _t(jnp.sqrt(_a(self.variance)))

    def sample(self, shape=()):
        """Detached draw. Families with a reparameterized rsample inherit
        this (sample = stop-gradient rsample, torch/paddle semantics);
        discrete families override sample directly."""
        from ..autograd import no_grad
        with no_grad():
            out = self.rsample(shape)
        out.stop_gradient = True
        return out

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from .. import ops
        return ops.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    """reference: distribution/normal.py."""

    def __init__(self, loc, scale, name=None):
        self.loc = _t(loc)
        self.scale = _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        from .. import ops
        return ops.square(self.scale)

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.loc), _a(self.scale))
        eps = Tensor(jax.random.normal(next_key(), shp, jnp.float32))
        return self.loc + self.scale * eps

    def log_prob(self, value):
        def f(v, loc, scale):
            return jax.scipy.stats.norm.logpdf(v, loc, scale)
        return apply_op("normal_log_prob", f, _t(value), self.loc, self.scale)

    def entropy(self):
        def f(scale):
            return 0.5 + 0.5 * jnp.log(2 * jnp.pi) + jnp.log(scale) + \
                jnp.zeros(self.batch_shape)
        return apply_op("normal_entropy", f, self.scale)

    def cdf(self, value):
        def f(v, loc, scale):
            return jax.scipy.stats.norm.cdf(v, loc, scale)
        return apply_op("normal_cdf", f, _t(value), self.loc, self.scale)

    def icdf(self, value):
        def f(v, loc, scale):
            return loc + scale * jax.scipy.special.ndtri(v)
        return apply_op("normal_icdf", f, _t(value), self.loc, self.scale)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _t(loc), _t(scale)
        self._base = Normal(loc, scale)
        super().__init__(self._base.batch_shape)

    @property
    def mean(self):
        return _t(jnp.exp(_a(self.loc) + _a(self.scale) ** 2 / 2))

    @property
    def variance(self):
        s2 = _a(self.scale) ** 2
        return _t((jnp.exp(s2) - 1) * jnp.exp(2 * _a(self.loc) + s2))

    def sample(self, shape=()):
        from .. import ops
        return ops.exp(self._base.sample(shape))

    def rsample(self, shape=()):
        from .. import ops
        return ops.exp(self._base.rsample(shape))

    def log_prob(self, value):
        def f(v, loc, scale):
            return jax.scipy.stats.norm.logpdf(jnp.log(v), loc, scale) - \
                jnp.log(v)
        return apply_op("lognormal_log_prob", f, _t(value), self.loc,
                        self.scale)

    def entropy(self):
        return self._base.entropy() + self.loc


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low, self.high = _t(low), _t(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape,
                                              self.high.shape))

    @property
    def mean(self):
        return (self.low + self.high) / 2.0

    @property
    def variance(self):
        from .. import ops
        return ops.square(self.high - self.low) / 12.0

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.low), _a(self.high))
        u = jax.random.uniform(next_key(), shp, jnp.float32)

        def f(lo, hi):
            return lo + (hi - lo) * u
        return apply_op("uniform_rsample", f, self.low, self.high)

    def log_prob(self, value):
        def f(v, lo, hi):
            inside = (v >= lo) & (v < hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)
        return apply_op("uniform_log_prob", f, _t(value), self.low, self.high)

    def entropy(self):
        from .. import ops
        return ops.log(self.high - self.low)


class Categorical(Distribution):
    """reference: distribution/categorical.py (constructed from logits)."""

    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("Categorical needs logits or probs")
        # normalize through dispatch so grads reach the user's param Tensor
        if logits is not None:
            self.logits = apply_op(
                "categorical_normalize",
                lambda a: a - jax.scipy.special.logsumexp(a, -1,
                                                          keepdims=True),
                _t(logits))
        else:
            self.logits = apply_op(
                "categorical_normalize",
                lambda p: jnp.log(jnp.maximum(p / p.sum(-1, keepdims=True),
                                              1e-37)),
                _t(probs))
        super().__init__(self.logits.shape[:-1])

    @property
    def _log_p(self):
        return _a(self.logits)

    @property
    def probs(self):
        from .. import ops
        return ops.exp(self.logits)

    def sample(self, shape=()):
        out = jax.random.categorical(next_key(), self._log_p,
                                     shape=tuple(shape) + self.batch_shape)
        return Tensor(out.astype(jnp.int64))

    def log_prob(self, value):
        def f(lp, v):
            lp = jnp.broadcast_to(lp, v.shape + lp.shape[-1:])
            return jnp.take_along_axis(
                lp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return apply_op("categorical_log_prob", f, self.logits,
                        _t(value, jnp.int32))

    def entropy(self):
        def f(lp):
            return -(jnp.exp(lp) * lp).sum(-1)
        return apply_op("categorical_entropy", f, self.logits)


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is None and logits is None:
            raise ValueError("Bernoulli needs probs or logits")
        # derive the other parameterization through dispatch so log_prob /
        # entropy gradients reach whichever Tensor the user actually passed
        if probs is not None:
            self.probs = _t(probs)
            self.logits = apply_op(
                "bernoulli_logits",
                lambda p: (lambda c: jnp.log(c) - jnp.log1p(-c))(
                    jnp.clip(p, 1e-7, 1 - 1e-7)),
                self.probs)
        else:
            self.logits = _t(logits)
            self.probs = apply_op("bernoulli_probs", jax.nn.sigmoid,
                                  self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return self.probs

    @property
    def variance(self):
        return apply_op("bernoulli_variance", lambda p: p * (1 - p),
                        self.probs)

    def sample(self, shape=()):
        shp = _shape(shape, _a(self.probs))
        out = jax.random.bernoulli(next_key(), _a(self.probs), shp)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def f(v, logit):
            return v * jax.nn.log_sigmoid(logit) + \
                (1 - v) * jax.nn.log_sigmoid(-logit)
        return apply_op("bernoulli_log_prob", f, _t(value), self.logits)

    def entropy(self):
        # xlogy form: 0*log(0) -> 0, so saturated probs give entropy 0, not nan
        def f(p):
            xlogy = jax.scipy.special.xlogy
            return -(xlogy(p, p) + xlogy(1 - p, 1 - p))
        return apply_op("bernoulli_entropy", f, self.probs)


def _cb_log_norm(p):
    """log C(p) for the continuous Bernoulli (Taylor-stabilized near 0.5)."""
    far = jnp.abs(p - 0.5) > 1e-3
    safe = jnp.where(far, p, 0.4)
    c = jnp.where(far,
                  2 * jnp.arctanh(1 - 2 * safe) / (1 - 2 * safe),
                  2.0 + (p - 0.5) ** 2 * 8.0 / 3.0)
    return jnp.log(c)


def _cb_mean(p):
    """E[X] = p/(2p-1) + 1/(2 arctanh(1-2p)); -> 0.5 at p = 0.5."""
    far = jnp.abs(p - 0.5) > 1e-3
    safe = jnp.where(far, p, 0.4)
    mu = safe / (2 * safe - 1) + 1 / (2 * jnp.arctanh(1 - 2 * safe))
    return jnp.where(far, mu, 0.5 + (p - 0.5) / 3.0)


class ContinuousBernoulli(Bernoulli):
    """reference: distribution/continuous_bernoulli.py (log-normalizer added)."""

    @property
    def mean(self):
        return apply_op("cb_mean", _cb_mean, self.probs)

    def log_prob(self, value):
        def f(v, p):
            base = v * jnp.log(p) + (1 - v) * jnp.log1p(-p)
            return base + _cb_log_norm(p)
        return apply_op("cb_log_prob", f, _t(value), self.probs)

    def rsample(self, shape=()):
        # inverse-CDF reparameterization: x = [log(u(2p-1)+1-p) - log(1-p)]
        #                                     / [log p - log(1-p)],  u~U(0,1)
        shp = _shape(shape, _a(self.probs))
        u = jax.random.uniform(next_key(), shp, jnp.float32, 1e-6, 1 - 1e-6)

        def f(p):
            far = jnp.abs(p - 0.5) > 1e-3
            safe = jnp.where(far, p, 0.4)
            x = ((jnp.log1p(u * (2 * safe - 1) - safe) - jnp.log1p(-safe))
                 / (jnp.log(safe) - jnp.log1p(-safe)))
            return jnp.where(far, x, u)
        return apply_op("cb_rsample", f, self.probs)

    def sample(self, shape=()):
        return Distribution.sample(self, shape)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha, self.beta = _t(alpha), _t(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape,
                                              self.beta.shape))

    @property
    def mean(self):
        a, b = _a(self.alpha), _a(self.beta)
        return Tensor(a / (a + b))

    @property
    def variance(self):
        a, b = _a(self.alpha), _a(self.beta)
        return Tensor(a * b / ((a + b) ** 2 * (a + b + 1)))

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.alpha), _a(self.beta))
        key = next_key()

        def f(a, b):  # implicit reparameterization via jax.random.beta grads
            return jax.random.beta(key, a, b, shp)
        return apply_op("beta_rsample", f, self.alpha, self.beta)

    def log_prob(self, value):
        def f(v, a, b):
            return jax.scipy.stats.beta.logpdf(v, a, b)
        return apply_op("beta_log_prob", f, _t(value), self.alpha, self.beta)

    def entropy(self):
        def f(a, b):
            return (jax.scipy.special.betaln(a, b)
                    - (a - 1) * jax.scipy.special.digamma(a)
                    - (b - 1) * jax.scipy.special.digamma(b)
                    + (a + b - 2) * jax.scipy.special.digamma(a + b))
        return apply_op("beta_entropy", f, self.alpha, self.beta)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration, self.rate = _t(concentration), _t(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return Tensor(_a(self.concentration) / _a(self.rate))

    @property
    def variance(self):
        return Tensor(_a(self.concentration) / _a(self.rate) ** 2)

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.concentration), _a(self.rate))
        key = next_key()

        def f(a, r):  # implicit reparameterization via jax.random.gamma grads
            return jax.random.gamma(key, a, shp) / r
        return apply_op("gamma_rsample", f, self.concentration, self.rate)

    def log_prob(self, value):
        def f(v, a, r):
            return jax.scipy.stats.gamma.logpdf(v, a, scale=1.0 / r)
        return apply_op("gamma_log_prob", f, _t(value), self.concentration,
                        self.rate)

    def entropy(self):
        def f(a, r):
            return a - jnp.log(r) + jax.scipy.special.gammaln(a) + \
                (1 - a) * jax.scipy.special.digamma(a)
        return apply_op("gamma_entropy", f, self.concentration, self.rate)


class Chi2(Gamma):
    def __init__(self, df, name=None):
        self.df = _t(df)
        super().__init__(self.df * 0.5, 0.5)  # Tensor op: keeps df grads


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / _a(self.rate))

    @property
    def variance(self):
        return Tensor(1.0 / _a(self.rate) ** 2)

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.rate))
        u = jax.random.exponential(next_key(), shp, jnp.float32)

        def f(r):
            return u / r
        return apply_op("exponential_rsample", f, self.rate)

    def log_prob(self, value):
        def f(v, r):
            return jnp.where(v >= 0, jnp.log(r) - r * v, -jnp.inf)
        return apply_op("exponential_log_prob", f, _t(value), self.rate)

    def entropy(self):
        from .. import ops
        return 1.0 - ops.log(self.rate)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _t(loc), _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return Tensor(2 * _a(self.scale) ** 2)

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.loc), _a(self.scale))
        eps = jax.random.laplace(next_key(), shp, jnp.float32)

        def f(loc, scale):
            return loc + scale * eps
        return apply_op("laplace_rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)
        return apply_op("laplace_log_prob", f, _t(value), self.loc, self.scale)

    def entropy(self):
        from .. import ops
        return 1.0 + ops.log(2.0 * self.scale)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _t(loc), _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.loc), _a(self.scale))
        eps = jax.random.cauchy(next_key(), shp, jnp.float32)

        def f(loc, scale):
            return loc + scale * eps
        return apply_op("cauchy_rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            return jax.scipy.stats.cauchy.logpdf(v, loc, scale)
        return apply_op("cauchy_log_prob", f, _t(value), self.loc, self.scale)

    def entropy(self):
        from .. import ops
        return ops.log(4.0 * math.pi * self.scale)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc, self.scale = _t(loc), _t(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape,
                                              self.scale.shape))

    @property
    def mean(self):
        return Tensor(_a(self.loc) + _a(self.scale) * np.euler_gamma)

    @property
    def variance(self):
        return Tensor((math.pi ** 2 / 6) * _a(self.scale) ** 2)

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.loc), _a(self.scale))
        eps = jax.random.gumbel(next_key(), shp, jnp.float32)

        def f(loc, scale):
            return loc + scale * eps
        return apply_op("gumbel_rsample", f, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)
        return apply_op("gumbel_log_prob", f, _t(value), self.loc, self.scale)

    def entropy(self):
        from .. import ops
        return ops.log(self.scale) + (1.0 + np.euler_gamma)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df, self.loc, self.scale = _t(df), _t(loc), _t(scale)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    def rsample(self, shape=()):
        shp = _shape(shape, _a(self.df), _a(self.loc), _a(self.scale))
        key = next_key()

        def f(df, loc, scale):  # df grads via gamma implicit reparam
            return loc + scale * jax.random.t(key, df, shp)
        return apply_op("studentt_rsample", f, self.df, self.loc, self.scale)

    def log_prob(self, value):
        def f(v, df, loc, scale):
            return jax.scipy.stats.t.logpdf(v, df, loc, scale)
        return apply_op("studentt_log_prob", f, _t(value), self.df, self.loc,
                        self.scale)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _t(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    def sample(self, shape=()):
        shp = _shape(shape, _a(self.rate))
        out = jax.random.poisson(next_key(), _a(self.rate), shp)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def f(v, r):
            return jax.scipy.stats.poisson.logpmf(v, r)
        return apply_op("poisson_log_prob", f, _t(value), self.rate)


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (reference geometric.py)."""

    def __init__(self, probs, name=None):
        self.probs = _t(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        p = _a(self.probs)
        return Tensor((1 - p) / p)

    def sample(self, shape=()):
        shp = _shape(shape, _a(self.probs))
        u = jax.random.uniform(next_key(), shp, jnp.float32, 1e-7, 1 - 1e-7)
        return Tensor(jnp.floor(jnp.log1p(-u) / jnp.log1p(-_a(self.probs))))

    def log_prob(self, value):
        def f(v, p):
            return v * jnp.log1p(-p) + jnp.log(p)
        return apply_op("geometric_log_prob", f, _t(value), self.probs)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _t(total_count)
        self.probs = _t(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape,
                                              self.probs.shape))

    @property
    def mean(self):
        return Tensor(_a(self.total_count) * _a(self.probs))

    def sample(self, shape=()):
        shp = _shape(shape, _a(self.total_count), _a(self.probs))
        out = jax.random.binomial(next_key(), _a(self.total_count),
                                  _a(self.probs), shape=shp)
        return Tensor(out.astype(jnp.float32))

    def log_prob(self, value):
        def f(v, n, p):
            return (jax.scipy.special.gammaln(n + 1)
                    - jax.scipy.special.gammaln(v + 1)
                    - jax.scipy.special.gammaln(n - v + 1)
                    + v * jnp.log(p) + (n - v) * jnp.log1p(-p))
        return apply_op("binomial_log_prob", f, _t(value), self.total_count,
                        self.probs)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs = _t(probs)
        super().__init__(self.probs.shape[:-1],
                         (self.probs.shape[-1],))

    def sample(self, shape=()):
        p = _a(self.probs)
        logits = jnp.log(jnp.maximum(p, 1e-37))
        draws = jax.random.categorical(
            next_key(), logits,
            shape=(self.total_count,) + tuple(shape) + self.batch_shape)
        onehot = jax.nn.one_hot(draws, p.shape[-1])
        return Tensor(onehot.sum(0))

    def log_prob(self, value):
        def f(v, p):
            n = v.sum(-1)
            return (jax.scipy.special.gammaln(n + 1)
                    - jax.scipy.special.gammaln(v + 1).sum(-1)
                    + (v * jnp.log(jnp.maximum(p, 1e-37))).sum(-1))
        return apply_op("multinomial_log_prob", f, _t(value), self.probs)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _t(concentration)
        super().__init__(self.concentration.shape[:-1],
                         (self.concentration.shape[-1],))

    @property
    def mean(self):
        a = _a(self.concentration)
        return Tensor(a / a.sum(-1, keepdims=True))

    def rsample(self, shape=()):
        key = next_key()
        shp = tuple(shape) + self.batch_shape

        def f(a):  # implicit reparameterization via gamma grads
            return jax.random.dirichlet(key, a, shp)
        return apply_op("dirichlet_rsample", f, self.concentration)

    def log_prob(self, value):
        def f(v, a):
            return ((a - 1) * jnp.log(v)).sum(-1) + \
                jax.scipy.special.gammaln(a.sum(-1)) - \
                jax.scipy.special.gammaln(a).sum(-1)
        return apply_op("dirichlet_log_prob", f, _t(value),
                        self.concentration)

    def entropy(self):
        def f(a):
            a0 = a.sum(-1)
            k = a.shape[-1]
            return (jax.scipy.special.gammaln(a).sum(-1)
                    - jax.scipy.special.gammaln(a0)
                    + (a0 - k) * jax.scipy.special.digamma(a0)
                    - ((a - 1) * jax.scipy.special.digamma(a)).sum(-1))
        return apply_op("dirichlet_entropy", f, self.concentration)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, scale_tril=None,
                 name=None):
        self.loc = _t(loc)
        if scale_tril is not None:
            self.scale_tril = _t(scale_tril)
        elif covariance_matrix is not None:
            self.scale_tril = apply_op("mvn_cholesky", jnp.linalg.cholesky,
                                       _t(covariance_matrix))
        else:
            raise ValueError("need covariance_matrix or scale_tril")
        super().__init__(self.loc.shape[:-1], (self.loc.shape[-1],))

    @property
    def _tril(self):
        return _a(self.scale_tril)

    @property
    def mean(self):
        return self.loc

    @property
    def covariance_matrix(self):
        return Tensor(self._tril @ jnp.swapaxes(self._tril, -2, -1))

    def rsample(self, shape=()):
        shp = tuple(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(next_key(), shp, jnp.float32)

        def f(loc, tril):
            return loc + jnp.einsum("...ij,...j->...i", tril, eps)
        return apply_op("mvn_rsample", f, self.loc, self.scale_tril)

    def log_prob(self, value):
        def f(v, loc, tril):
            d = v - loc
            z = jax.scipy.linalg.solve_triangular(tril, d[..., None],
                                                  lower=True)[..., 0]
            k = v.shape[-1]
            logdet = jnp.log(jnp.abs(jnp.diagonal(tril, axis1=-2,
                                                  axis2=-1))).sum(-1)
            return -0.5 * (z ** 2).sum(-1) - logdet - 0.5 * k * jnp.log(
                2 * jnp.pi)
        return apply_op("mvn_log_prob", f, _t(value), self.loc,
                        self.scale_tril)


class Independent(Distribution):
    """reference: distribution/independent.py — reinterpret batch dims as
    event dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = reinterpreted_batch_rank
        b = base.batch_shape
        super().__init__(b[:len(b) - self.rank],
                         b[len(b) - self.rank:] + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = self.base.log_prob(value)
        from .. import ops
        return ops.sum(lp, axis=list(range(len(lp.shape) - self.rank,
                                           len(lp.shape))))

    def entropy(self):
        ent = self.base.entropy()
        from .. import ops
        return ops.sum(ent, axis=list(range(len(ent.shape) - self.rank,
                                            len(ent.shape))))


class TransformedDistribution(Distribution):
    """reference: distribution/transformed_distribution.py (minimal: a list of
    transforms with .forward/.inverse/.forward_log_det_jacobian)."""

    def __init__(self, base, transforms):
        self.base = base
        self.transforms = list(transforms)
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        lp = None
        x = value
        for t in reversed(self.transforms):
            y = x
            x = t.inverse(y)
            ld = t.forward_log_det_jacobian(x)
            lp = ld if lp is None else lp + ld
        base_lp = self.base.log_prob(x)
        return base_lp - lp if lp is not None else base_lp


# ---- KL registry -------------------------------------------------------------
_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    """reference: distribution/kl.py register_kl decorator."""
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn
    return deco


def kl_divergence(p, q):
    # most-specific registered pair wins (minimal total MRO distance), so a
    # subclass with its own KL never falls back to its base's formula
    best_fn, best_score = None, None
    for (pc, qc), fn in _KL_REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            score = type(p).__mro__.index(pc) + type(q).__mro__.index(qc)
            if best_score is None or score < best_score:
                best_fn, best_score = fn, score
    if best_fn is None:
        raise NotImplementedError(
            f"no KL registered for ({type(p).__name__}, {type(q).__name__})")
    return best_fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def f(pl, ps, ql, qs):
        vr = (ps / qs) ** 2
        return 0.5 * (vr + ((pl - ql) / qs) ** 2 - 1 - jnp.log(vr))
    return apply_op("kl_normal", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def f(pl, ph, ql, qh):
        out = jnp.log((qh - ql) / (ph - pl))
        ok = (ql <= pl) & (ph <= qh)
        return jnp.where(ok, out, jnp.inf)
    return apply_op("kl_uniform", f, p.low, p.high, q.low, q.high)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    def f(plp, qlp):
        return (jnp.exp(plp) * (plp - qlp)).sum(-1)
    return apply_op("kl_categorical", f, p.logits, q.logits)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def f(pp, qp):
        pp = jnp.clip(pp, 1e-7, 1 - 1e-7)
        qp = jnp.clip(qp, 1e-7, 1 - 1e-7)
        return pp * (jnp.log(pp) - jnp.log(qp)) + \
            (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp))
    return apply_op("kl_bernoulli", f, p.probs, q.probs)


@register_kl(ContinuousBernoulli, ContinuousBernoulli)
def _kl_continuous_bernoulli(p, q):
    # KL = logC(p) - logC(q) + mu_p*(log p - log q)
    #      + (1-mu_p)*(log(1-p) - log(1-q))
    def f(pp, qp):
        pp = jnp.clip(pp, 1e-6, 1 - 1e-6)
        qp = jnp.clip(qp, 1e-6, 1 - 1e-6)
        mu = _cb_mean(pp)
        return (_cb_log_norm(pp) - _cb_log_norm(qp)
                + mu * (jnp.log(pp) - jnp.log(qp))
                + (1 - mu) * (jnp.log1p(-pp) - jnp.log1p(-qp)))
    return apply_op("kl_cbernoulli", f, p.probs, q.probs)


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def f(pa, pb, qa, qb):
        dg = jax.scipy.special.digamma
        bl = jax.scipy.special.betaln
        return (bl(qa, qb) - bl(pa, pb)
                + (pa - qa) * dg(pa) + (pb - qb) * dg(pb)
                + (qa - pa + qb - pb) * dg(pa + pb))
    return apply_op("kl_beta", f, p.alpha, p.beta, q.alpha, q.beta)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def f(pa, pr, qa, qr):
        dg = jax.scipy.special.digamma
        gl = jax.scipy.special.gammaln
        return ((pa - qa) * dg(pa) - gl(pa) + gl(qa)
                + qa * (jnp.log(pr) - jnp.log(qr))
                + pa * (qr - pr) / pr)
    return apply_op("kl_gamma", f, p.concentration, p.rate, q.concentration,
                    q.rate)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    def f(pr, qr):
        return jnp.log(pr) - jnp.log(qr) + qr / pr - 1
    return apply_op("kl_exponential", f, p.rate, q.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    def f(pl, ps, ql, qs):
        d = jnp.abs(pl - ql)
        return (jnp.log(qs) - jnp.log(ps)
                + (ps * jnp.exp(-d / ps) + d) / qs - 1)
    return apply_op("kl_laplace", f, p.loc, p.scale, q.loc, q.scale)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def f(pa, qa):
        dg = jax.scipy.special.digamma
        gl = jax.scipy.special.gammaln
        p0 = pa.sum(-1)
        return (gl(p0) - gl(pa).sum(-1)
                - gl(qa.sum(-1)) + gl(qa).sum(-1)
                + ((pa - qa) * (dg(pa) - dg(p0)[..., None])).sum(-1))
    return apply_op("kl_dirichlet", f, p.concentration, q.concentration)


class ExponentialFamily(Distribution):
    """reference distribution/exponential_family.py: base for natural-
    parameter families; entropy via the Bregman identity when a subclass
    provides natural parameters + log normalizer."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0

    def entropy(self):
        """H = A(eta) - sum_i eta_i * dA/deta_i - E[log h(x)], with the
        sufficient-statistic means obtained by autodiff of the log normalizer
        (the reference's Bregman-divergence trick)."""
        import jax
        nat = [(_a(p) if isinstance(p, Tensor) else jnp.asarray(p))
               for p in self._natural_parameters]
        lg = lambda *ps: jnp.sum(self._log_normalizer(*ps))
        a_val = self._log_normalizer(*nat)
        grads = jax.grad(lg, argnums=tuple(range(len(nat))))(*nat)
        ent = a_val - self._mean_carrier_measure
        bs = tuple(self.batch_shape)
        for eta, g in zip(nat, grads):
            term = (eta * g).reshape(bs + (-1,)).sum(-1) if bs else \
                jnp.sum(eta * g)
            ent = ent - term
        return _t(ent)


class LKJCholesky(Distribution):
    """reference distribution/lkj_cholesky.py: distribution over Cholesky
    factors of correlation matrices (LKJ(eta)); onion-method sampling."""

    def __init__(self, dim, concentration=1.0, sample_method="onion"):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        self.dim = dim
        self.concentration = float(_a(concentration)) if isinstance(
            concentration, Tensor) else float(concentration)
        super().__init__(batch_shape=(), event_shape=(dim, dim))

    def sample(self, shape=()):
        import jax
        from ..core.rng import next_key
        shape = tuple(shape)
        d, eta = self.dim, self.concentration
        key = next_key()
        # onion method (Lewandowski et al. 2009): build row by row
        L = jnp.zeros(shape + (d, d))
        L = L.at[..., 0, 0].set(1.0)
        for i in range(1, d):
            key, k1, k2 = jax.random.split(key, 3)
            beta_ab = eta + (d - 1 - i) / 2.0
            y = jax.random.beta(k1, i / 2.0, beta_ab, shape)   # squared radius
            u = jax.random.normal(k2, shape + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            L = L.at[..., i, :i].set(jnp.sqrt(y)[..., None] * u)
            L = L.at[..., i, i].set(jnp.sqrt(1 - y))
        return _t(L)

    def log_prob(self, value):
        """log p(L) for a Cholesky factor of a correlation matrix
        (reference/torch LKJCholesky.log_prob closed form)."""
        import scipy.special as ss
        import math as _m
        d, eta = self.dim, self.concentration
        Lv = _a(value) if isinstance(value, Tensor) else jnp.asarray(value)
        diag = jnp.diagonal(Lv, axis1=-2, axis2=-1)[..., 1:]
        order = np.arange(2, d + 1)
        exponents = jnp.asarray(d - order + 2 * eta - 2, jnp.float32)
        unnorm = jnp.sum(exponents * jnp.log(jnp.maximum(diag, 1e-30)), -1)
        dm1 = d - 1
        alpha = eta + 0.5 * dm1
        norm = (0.5 * dm1 * _m.log(_m.pi)
                + float(ss.multigammaln(alpha - 0.5, dm1))
                - dm1 * float(ss.gammaln(alpha)))
        return _t(unnorm - norm)
