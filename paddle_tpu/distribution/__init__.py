"""paddle.distribution (reference: python/paddle/distribution, 9.3k LoC).
Normal/Uniform/Categorical etc. land later this round."""
