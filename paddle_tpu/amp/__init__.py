"""AMP — autocast + GradScaler (reference: python/paddle/amp/auto_cast.py:1029,
grad_scaler.py:657; C++ autocast state imperative/amp_auto_cast.h:29).

On TPU bf16 is the native fast dtype: no loss scaling needed (GradScaler becomes a
pass-through unless fp16 is requested), and autocast is a dispatch-level dtype cast
per the O1 white/black lists.
"""
from __future__ import annotations

import contextlib

import numpy as np
import jax.numpy as jnp

from ..core.dispatch import _state, unwrap
from ..core import dtype as dtypes
from ..core.tensor import Tensor

# O1 lists (reference: python/paddle/amp/amp_lists.py WHITE_LIST/BLACK_LIST)
WHITE_LIST = {
    "matmul", "mm", "bmm", "mv", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "scaled_dot_product_attention", "flash_attention", "addmm", "embedding",
}
BLACK_LIST = {
    "cross_entropy", "softmax_with_cross_entropy", "nll_loss", "log_softmax",
    "exp", "log", "log2", "log10", "log1p", "expm1", "pow", "square", "sqrt",
    "rsqrt", "p_norm", "norm", "cumsum", "cumprod", "logsumexp", "erf", "erfinv",
    "sum", "mean_all", "softmax_grad_blk",
    "layer_norm", "rms_norm", "batch_norm", "group_norm", "instance_norm",
    "mse_loss", "l1_loss", "bce_with_logits", "binary_cross_entropy", "kl_div",
}


class AmpState:
    __slots__ = ("enable", "dtype", "level", "white", "black")

    def __init__(self, enable, dtype, level, white, black):
        self.enable = enable
        self.dtype = dtype
        self.level = level
        self.white = white
        self.black = black


def amp_state():
    return _state.amp_state


# FLAGS_low_precision_op_list audit (reference: common/flags.cc:55 +
# paddle.amp.debugging collect_operator_stats): {op_name: low-precision runs}
_low_precision_ops: dict = {}


def low_precision_op_list():
    """Ops that ran with inputs cast to the low dtype while the
    FLAGS_low_precision_op_list flag was non-zero."""
    return dict(_low_precision_ops)


def clear_low_precision_op_list():
    _low_precision_ops.clear()


def maybe_cast_inputs(op_name, arrays):
    """Called by dispatch: cast float arrays per autocast policy."""
    st = _state.amp_state
    if st is None or not st.enable:
        return arrays
    low = st.dtype
    if st.level == "O2":
        target = None if op_name in st.black else low
    else:  # O1
        if op_name in st.white:
            target = low
        elif op_name in st.black:
            target = np.dtype(np.float32)
        else:
            target = None  # follow inputs
    if target is None:
        return arrays
    if target == low:
        from ..core import flags as _flags
        if _flags.flag("low_precision_op_list"):
            _low_precision_ops[op_name] = _low_precision_ops.get(op_name,
                                                                 0) + 1
    out = []
    for a in arrays:
        if hasattr(a, "dtype") and dtypes.is_floating_point(a.dtype) \
                and np.dtype(a.dtype) != np.dtype(target):
            out.append(a.astype(target))
        else:
            out.append(a)
    return tuple(out)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast (reference: amp/auto_cast.py:1029)."""
    white = set(WHITE_LIST)
    black = set(BLACK_LIST)
    if custom_white_list:
        white |= set(custom_white_list)
        black -= set(custom_white_list)
    if custom_black_list:
        black |= set(custom_black_list)
        white -= set(custom_black_list)
    prev = _state.amp_state
    _state.amp_state = AmpState(enable, dtypes.convert_dtype(dtype), level, white, black)
    try:
        yield
    finally:
        _state.amp_state = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O2", dtype="bfloat16", master_weight=None,
             save_dtype=None):
    """paddle.amp.decorate — O2 casts parameters to the low dtype (master weights
    kept in f32 inside the optimizer accumulators automatically)."""
    single_model = not isinstance(models, (list, tuple))
    model_list = [models] if single_model else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
        for opt in ([optimizers] if optimizers is not None and
                    not isinstance(optimizers, (list, tuple)) else (optimizers or [])):
            opt._multi_precision = True
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """reference: python/paddle/amp/grad_scaler.py:657 (base AmpScaler:62).

    Dynamic loss scaling for fp16; for bf16 (TPU default) scaling is a no-op but
    the API surface (scale/step/update/minimize/unscale_) is preserved.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = False

    def scale(self, var):
        global _active_scaler
        _active_scaler = self if self._enable else None
        if not self._enable or self._scale == 1.0:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        global _active_scaler
        _active_scaler = None   # grads are unscaled from here on
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found_inf = False
        from ..core.selected_rows import SelectedRows
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            if isinstance(p.grad, SelectedRows):
                # row-sparse grad: unscale the values in place, keep sparsity
                sr = p.grad
                v = sr.values.astype(jnp.float32)
                if self._scale != 1.0:
                    v = v * inv
                if not bool(jnp.all(jnp.isfinite(v))):
                    found_inf = True
                p.grad = SelectedRows(sr.rows, v.astype(sr.values.dtype),
                                      sr.height)
                continue
            g = unwrap(p.grad).astype(jnp.float32)
            if self._scale != 1.0:
                g = g * inv
            finite = bool(jnp.all(jnp.isfinite(g)))
            if not finite:
                found_inf = True
            p.grad = Tensor(g.astype(unwrap(p.grad).dtype))
        self._found_inf = found_inf
        self._unscaled = True

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not self._unscaled:
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def update(self):
        global _active_scaler
        _active_scaler = None   # the scaled-backward window is over
        if not (self._enable and self._dynamic):
            self._unscaled = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False
        self._unscaled = False

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("good_steps", 0)
        self._bad_steps = sd.get("bad_steps", 0)


def is_bfloat16_supported(device=None):
    return True


def is_float16_supported(device=None):
    return True


# last enabled scaler that scaled a loss this process; lets out-of-band grad
# consumers (e.g. distributed.ps_sparse.PsEmbedding's backward-hook push)
# unscale gradients they receive mid-backward, before unscale_() has run
_active_scaler = None


def active_loss_scale() -> float:
    """Loss-scale factor currently applied to gradients flowing in backward
    (1.0 when no enabled GradScaler has scaled a loss)."""
    if _active_scaler is not None and _active_scaler._enable:
        return float(_active_scaler._scale)
    return 1.0
