"""paddle.device surface (reference: python/paddle/device/__init__.py)."""
from __future__ import annotations

import jax

from ..core.device import (set_device, get_device, current_place, device_count,  # noqa: F401
                           Place, is_compiled_with_cuda, is_compiled_with_xpu,
                           is_compiled_with_cinn)


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_custom_device():
    return []


def is_compiled_with_rocm():
    return False


def is_compiled_with_custom_device(name):
    return name in ("tpu", "axon")


def synchronize(device=None):
    """Block until all queued device work completes (stream sync analog)."""
    (jax.device_put(0) + 0).block_until_ready()


class Stream:
    """Streams are implicit on TPU (XLA manages ordering); API-compat no-op."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()

    def wait_event(self, event):
        pass


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


def stream_guard(stream):
    import contextlib
    return contextlib.nullcontext()


class cuda:
    """paddle.device.cuda compat namespace (maps to the accelerator)."""

    Stream = Stream
    Event = Event

    @staticmethod
    def device_count():
        return device_count()

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def current_stream(device=None):
        return Stream(device)

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("peak_bytes_in_use", 0)

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_in_use", 0)

    @staticmethod
    def memory_reserved(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get("bytes_reserved", stats.get("bytes_in_use", 0))

    @staticmethod
    def empty_cache():
        pass


def get_cudnn_version():
    """Compat: no cuDNN on the TPU build (reference returns None when absent)."""
    return None


def is_compiled_with_ipu():
    return False


def is_compiled_with_distribute():
    return True


def get_all_custom_device_type():
    """No out-of-tree device plugins: TPU is first-class here."""
    return []


class XPUPlace(Place):
    """Compat: Kunlun place; resolves to the default accelerator."""

    def __init__(self, device_id=0):
        import jax
        devs = jax.devices()
        super().__init__(devs[min(device_id, len(devs) - 1)])


class IPUPlace:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU support is not part of the TPU build")
