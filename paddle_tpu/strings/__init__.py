"""String tensors (reference: paddle/phi/core/string_tensor.h + the
phi/kernels/strings/ kernel family — strings_empty_kernel.h,
strings_copy_kernel.h, strings_lower_upper_kernel.h, case_utils.h/unicode.h).

TPU-native framing: a TPU has no string compute unit — the reference's GPU
string kernels exist to co-locate tokenization-adjacent preprocessing with
the CUDA pipeline. Here strings are HOST-resident (numpy object arrays) by
design; anything that needs device compute happens after numericalization.
The kernel surface matches the reference: empty/empty_like, copy, and
case conversion with the same ascii-vs-utf8 switch
(strings_lower_upper_kernel.h's bool use_utf8_encoding: the ascii path
touches only [A-Za-z]; the utf8 path applies full Unicode case mapping).
"""
from __future__ import annotations

import numpy as np

__all__ = ["StringTensor", "empty", "empty_like", "copy", "lower", "upper",
           "to_string_tensor"]


class StringTensor:
    """Dense tensor of variable-length UTF-8 strings (reference
    phi::StringTensor over pstring).

    Host-resident; `data` is an ndarray of python str with arbitrary shape.
    """

    def __init__(self, data, name=None):
        # forced copy: np.asarray would alias a caller's object ndarray and
        # the normalization below would mutate it in place
        arr = np.array(data, dtype=object)
        # normalize every element to str (bytes decode as UTF-8, matching
        # the reference's pstring semantics)
        flat = arr.reshape(-1)
        for i, v in enumerate(flat):
            if isinstance(v, bytes):
                flat[i] = v.decode("utf-8")
            elif not isinstance(v, str):
                flat[i] = str(v)
        self._data = flat.reshape(arr.shape)
        self.name = name

    # -- metadata (reference string_tensor.h: dims/numel/valid) --------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return "pstring"

    def numel(self):
        return int(self._data.size)

    def reshape(self, shape):
        return StringTensor(self._data.reshape(shape), name=self.name)

    def numpy(self):
        return self._data

    def tolist(self):
        return self._data.tolist()

    def __getitem__(self, idx):
        out = self._data[idx]
        if isinstance(out, str):
            return out
        return StringTensor(out)

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        other_arr = other._data if isinstance(other, StringTensor) else \
            np.asarray(other, dtype=object)
        return self._data == other_arr

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, data={self._data!r})"


def to_string_tensor(data, name=None) -> StringTensor:
    return StringTensor(data, name=name)


def empty(shape) -> StringTensor:
    """reference strings_empty_kernel.h EmptyKernel: uninitialized -> ""."""
    arr = np.empty(tuple(shape), dtype=object)
    arr.reshape(-1)[:] = ""
    return StringTensor(arr)


def empty_like(x: StringTensor) -> StringTensor:
    return empty(x.shape)


def copy(x: StringTensor) -> StringTensor:
    """reference strings_copy_kernel.h Copy (str values are immutable, so an
    element-wise array copy is a deep copy)."""
    return StringTensor(x._data.copy())


def _case_convert(x, fn_ascii, fn_unicode, use_utf8_encoding):
    flat = x._data.reshape(-1)
    out = np.empty_like(flat)
    for i, s in enumerate(flat):
        out[i] = fn_unicode(s) if use_utf8_encoding else fn_ascii(s)
    return StringTensor(out.reshape(x._data.shape))


def _ascii_lower(s: str) -> str:
    return "".join(chr(ord(c) + 32) if "A" <= c <= "Z" else c for c in s)


def _ascii_upper(s: str) -> str:
    return "".join(chr(ord(c) - 32) if "a" <= c <= "z" else c for c in s)


def lower(x: StringTensor, use_utf8_encoding: bool = False) -> StringTensor:
    """reference strings_lower_upper_kernel.h StringLowerKernel: ascii mode
    maps [A-Z] only; utf8 mode applies Unicode case mapping."""
    return _case_convert(x, _ascii_lower, str.lower, use_utf8_encoding)


def upper(x: StringTensor, use_utf8_encoding: bool = False) -> StringTensor:
    """reference strings_lower_upper_kernel.h StringUpperKernel."""
    return _case_convert(x, _ascii_upper, str.upper, use_utf8_encoding)
