"""paddle.sparse analog (reference: python/paddle/sparse — 5.6k LoC: COO/CSR
tensors + unary/binary/matmul/nn ops over phi sparse kernels).

TPU-native: storage is jax.experimental.sparse BCOO/BCSR — values stay sparse
end-to-end (no densifying). XLA lowers BCOO matmul to gather/segment-sum,
which is the right TPU formulation; value-wise ops map over .values() only."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..core.dispatch import unwrap

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "transpose", "reshape", "sum",
    "relu", "tanh", "sigmoid", "abs", "sin", "sinh", "asin", "asinh", "tan",
    "atan", "atanh", "sqrt", "square", "log1p", "expm1", "pow", "neg",
    "cast", "coalesce", "nn",
]


class SparseCooTensor:
    """COO sparse tensor over BCOO (reference phi SparseCooTensor).

    `values_t` (optional) is a tape-connected dense Tensor over the stored
    values: sparse.nn layers thread it through op dispatch so autograd flows
    from sparse outputs back to layer weights and input values."""

    def __init__(self, bcoo, stop_gradient=True, values_t=None):
        self._mat = bcoo
        self.stop_gradient = stop_gradient
        self._vt = values_t

    # ---- introspection ------------------------------------------------------
    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    @property
    def ndim(self):
        return len(self._mat.shape)

    def nnz(self):
        return int(self._mat.nse)

    def indices(self):
        return Tensor(self._mat.indices.T)      # [ndim, nnz] paddle layout

    def values(self):
        return self._vt if self._vt is not None else Tensor(self._mat.data)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def to_dense(self):
        return Tensor(self._mat.todense())

    def to_sparse_csr(self):
        mat = self._mat
        if len(mat.shape) == 3 and mat.n_batch == 0:
            # batched CSR (reference 3D CSR): leading dim becomes the batch
            mat = jsparse.bcoo_update_layout(mat, n_batch=1,
                                             on_inefficient=None)
        # layout conversion may reorder entries: thread the tape-connected
        # values through ONLY when the 2D COO indices are already row-major
        # sorted (then from_bcoo preserves order; otherwise values() on the
        # CSR would silently pair values with the wrong coordinates)
        vt = None
        if self._vt is not None and len(self._mat.shape) == 2:
            idx = np.asarray(self._mat.indices)
            keys = idx[:, 0].astype(np.int64) * int(self._mat.shape[1]) \
                + idx[:, 1]
            if len(keys) < 2 or bool((keys[1:] >= keys[:-1]).all()):
                vt = self._vt
        return SparseCsrTensor(jsparse.BCSR.from_bcoo(mat),
                               self.stop_gradient, values_t=vt)

    def coalesce(self):
        return SparseCooTensor(self._mat.sum_duplicates(
            nse=self._mat.nse), self.stop_gradient)

    def numpy(self):
        return np.asarray(self._mat.todense())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    # ---- arithmetic ---------------------------------------------------------
    def __add__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return subtract(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __matmul__(self, other):
        return matmul(self, other)

    def __neg__(self):
        return neg(self)

    def _map_values(self, fn):
        return SparseCooTensor(
            jsparse.BCOO((fn(self._mat.data), self._mat.indices),
                         shape=self._mat.shape), self.stop_gradient)


class SparseCsrTensor:
    """CSR sparse tensor over BCSR (reference phi SparseCsrTensor)."""

    def __init__(self, bcsr, stop_gradient=True, values_t=None):
        self._mat = bcsr
        self.stop_gradient = stop_gradient
        self._vt = values_t

    @property
    def shape(self):
        return list(self._mat.shape)

    @property
    def dtype(self):
        return self._mat.dtype

    def nnz(self):
        return int(self._mat.nse)

    def crows(self):
        return Tensor(self._mat.indptr)

    def cols(self):
        return Tensor(self._mat.indices)

    def values(self):
        return self._vt if self._vt is not None else Tensor(self._mat.data)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def to_dense(self):
        return Tensor(self._mat.todense())

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._mat.to_bcoo(), self.stop_gradient,
                               values_t=self._vt)

    def numpy(self):
        return np.asarray(self._mat.todense())

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


# ---- creation ----------------------------------------------------------------
def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """reference: python/paddle/sparse/creation.py sparse_coo_tensor.
    indices: [ndim, nnz]; values: [nnz, ...]."""
    idx = np.asarray(unwrap(indices) if isinstance(indices, Tensor)
                     else indices)
    vt = values if isinstance(values, Tensor) else None
    v = unwrap(values) if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        v = v.astype(dtype)
        vt = None
    if vt is not None and not stop_gradient and vt.stop_gradient:
        # fresh view over the same buffer: don't mutate the caller's tensor
        vt = Tensor(vt._buf, stop_gradient=False)
    if shape is None:
        shape = tuple(int(i) + 1 for i in idx.max(axis=1))
    mat = jsparse.BCOO((v, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(mat, stop_gradient, values_t=vt)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """reference: sparse/creation.py sparse_csr_tensor."""
    indptr = jnp.asarray(np.asarray(unwrap(crows) if isinstance(crows, Tensor)
                                    else crows))
    idx = jnp.asarray(np.asarray(unwrap(cols) if isinstance(cols, Tensor)
                                 else cols))
    vt = values if isinstance(values, Tensor) else None
    v = unwrap(values) if isinstance(values, Tensor) else jnp.asarray(values)
    if dtype is not None:
        v = v.astype(dtype)
        vt = None
    if vt is not None and not stop_gradient and vt.stop_gradient:
        vt = Tensor(vt._buf, stop_gradient=False)
    mat = jsparse.BCSR((v, idx, indptr), shape=tuple(shape))
    return SparseCsrTensor(mat, stop_gradient, values_t=vt)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _coo(x):
    if isinstance(x, SparseCooTensor):
        return x._mat
    if isinstance(x, SparseCsrTensor):
        return x._mat.to_bcoo()
    raise TypeError(f"expected sparse tensor, got {type(x)}")


# ---- binary ------------------------------------------------------------------
def add(x, y, name=None):
    if isinstance(y, (Tensor, jnp.ndarray, np.ndarray)):
        return Tensor(_coo(x).todense() + unwrap(y))
    a, b = _coo(x), _coo(y)
    if not is_same_shape(x, y):
        raise ValueError(
            f"sparse add needs same shapes, got {x.shape} vs {y.shape}")
    # union of patterns: concatenate entries then merge duplicates
    out = jsparse.BCOO((jnp.concatenate([a.data, b.data]),
                        jnp.concatenate([a.indices, b.indices])),
                       shape=a.shape)
    return SparseCooTensor(out.sum_duplicates(nse=out.nse))


def subtract(x, y, name=None):
    if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        return add(x, multiply(y, -1.0))
    return Tensor(_coo(x).todense() - unwrap(y))


def multiply(x, y, name=None):
    if np.isscalar(y):
        if isinstance(x, SparseCooTensor):
            return x._map_values(lambda v: v * y)
        return SparseCsrTensor(jsparse.BCSR(
            (x._mat.data * y, x._mat.indices, x._mat.indptr),
            shape=tuple(x._mat.shape)))
    # elementwise with dense: gather dense values at nnz coordinates
    if isinstance(y, (Tensor, jnp.ndarray, np.ndarray)):
        m = _coo(x)
        d = unwrap(y) if isinstance(y, Tensor) else jnp.asarray(y)
        gathered = d[tuple(m.indices[:, i] for i in range(m.indices.shape[1]))]
        return SparseCooTensor(jsparse.BCOO((m.data * gathered, m.indices),
                                            shape=m.shape))
    # sparse*sparse
    a, b = _coo(x).sum_duplicates(), _coo(y).sum_duplicates()
    return SparseCooTensor(jsparse.bcoo_multiply_sparse(a, b))


def divide(x, y, name=None):
    if np.isscalar(y):
        return multiply(x, 1.0 / y)
    m = _coo(x)
    d = unwrap(y) if isinstance(y, Tensor) else jnp.asarray(y)
    gathered = d[tuple(m.indices[:, i] for i in range(m.indices.shape[1]))]
    return SparseCooTensor(jsparse.BCOO((m.data / gathered, m.indices),
                                        shape=m.shape))


def matmul(x, y, name=None):
    """sparse @ dense → dense (reference sparse/binary.py matmul); XLA lowers
    BCOO dot_general to gather + segment-sum."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        d = unwrap(y) if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x._mat @ d)
    d = unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(d @ y._mat)


def masked_matmul(x, y, mask, name=None):
    """dense@dense evaluated only at mask's nnz coordinates (reference
    sparse masked_matmul — SDDMM)."""
    a = unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)
    b = unwrap(y) if isinstance(y, Tensor) else jnp.asarray(y)
    m = _coo(mask)
    rows = m.indices[:, 0]
    cols = m.indices[:, 1]
    vals = jnp.einsum("nk,nk->n", a[rows, :], b[:, cols].T)
    return SparseCooTensor(jsparse.BCOO((vals, m.indices), shape=m.shape))


def transpose(x, perm, name=None):
    m = _coo(x)
    return SparseCooTensor(jsparse.bcoo_transpose(m, permutation=tuple(perm)))


def reshape(x, shape, name=None):
    m = _coo(x)
    return SparseCooTensor(jsparse.bcoo_reshape(m, new_sizes=tuple(shape)))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    m = _coo(x)
    if dtype is not None:
        m = jsparse.BCOO((m.data.astype(dtype), m.indices), shape=m.shape)
    if axis is None:
        return Tensor(m.data.sum())
    axes = (axis,) if np.isscalar(axis) else tuple(axis)
    axes = tuple(a % len(m.shape) for a in axes)  # bcoo asserts a >= 0
    out = jsparse.bcoo_reduce_sum(m, axes=axes)
    if keepdim:
        kept = tuple(1 if i in axes else s for i, s in enumerate(m.shape))
        out = jsparse.bcoo_reshape(out, new_sizes=kept)
    return SparseCooTensor(out)


def coalesce(x, name=None):
    return x.coalesce()


def cast(x, index_dtype=None, value_dtype=None, name=None):
    m = _coo(x)
    data = m.data.astype(value_dtype) if value_dtype else m.data
    idx = m.indices.astype(index_dtype) if index_dtype else m.indices
    return SparseCooTensor(jsparse.BCOO((data, idx), shape=m.shape))


# ---- value-wise unary (sparsity-preserving: f(0)=0 family) -------------------
def _unary(name, jfn):
    def op(x, name_=None):
        return x._map_values(jfn) if isinstance(x, SparseCooTensor) else \
            SparseCooTensor(_coo(x))._map_values(jfn)
    op.__name__ = name
    return op


relu = _unary("relu", jax.nn.relu)
tanh = _unary("tanh", jnp.tanh)
sin = _unary("sin", jnp.sin)
sinh = _unary("sinh", jnp.sinh)
asin = _unary("asin", jnp.arcsin)
asinh = _unary("asinh", jnp.arcsinh)
tan = _unary("tan", jnp.tan)
atan = _unary("atan", jnp.arctan)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
expm1 = _unary("expm1", jnp.expm1)
neg = _unary("neg", jnp.negative)
abs = _unary("abs", jnp.abs)


def sigmoid(x, name=None):
    # not zero-preserving: densifies by definition
    return Tensor(jax.nn.sigmoid(_coo(x).todense()))


def pow(x, factor, name=None):
    if not isinstance(x, SparseCooTensor):
        x = SparseCooTensor(_coo(x))
    return x._map_values(lambda v: jnp.power(v, factor))


from . import nn  # real sparse.nn subpackage (conv/pool/norm/activation)  # noqa: E402


deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
isnan = _unary("isnan", jnp.isnan)


def mv(x, vec, name=None):
    """Sparse matrix x dense vector (reference sparse/binary.py mv)."""
    v = unwrap(vec)
    return Tensor(_coo(x) @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta * input + alpha * (x @ y) with sparse x (reference addmm)."""
    dense_in = unwrap(input)
    yv = unwrap(y)
    return Tensor(beta * dense_in + alpha * (_coo(x) @ yv))


def mask_as(x, mask, name=None):
    """Keep x's entries at mask's nonzero coordinates (reference mask_as)."""
    if isinstance(mask, (SparseCooTensor, SparseCsrTensor)):
        idx = _coo(mask).indices.T                      # [ndim, nnz]
    else:
        mm = unwrap(mask)
        idx = jnp.stack(jnp.nonzero(mm != 0), axis=0)
    xv = unwrap(x) if isinstance(x, Tensor) else _coo(x).todense()
    vals = xv[tuple(idx)]
    return sparse_coo_tensor(idx, vals, xv.shape)


def slice(x, axes, starts, ends, name=None):
    """Dense-slice a sparse tensor, result sparse (reference sparse slice)."""
    import builtins
    dense = _coo(x).todense()
    slices = [builtins.slice(None)] * dense.ndim
    for ax, s, e in zip(axes, starts, ends):
        slices[ax] = builtins.slice(int(s), int(e))
    out = dense[tuple(slices)]
    idx = jnp.stack(jnp.nonzero(out != 0), axis=0)
    return sparse_coo_tensor(idx, out[tuple(idx)], out.shape)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """reference sparse pca_lowrank: densify (tiny factor matrices) and run
    the dense routine."""
    from ..ops.linalg_extra import pca_lowrank as _dense_pca
    dense = Tensor(_coo(x).todense())
    return _dense_pca(dense, q=q, center=center, niter=niter)
