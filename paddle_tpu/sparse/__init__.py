"""paddle.sparse (reference: python/paddle/sparse) — COO/CSR tensors.
JAX BCOO-backed implementation lands later this round; importable stubs now."""


def sparse_coo_tensor(indices, values, shape=None, **kw):
    from jax.experimental import sparse as jsparse
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from ..core.dispatch import unwrap
    idx = unwrap(indices)
    v = unwrap(values)
    mat = jsparse.BCOO((v, jnp.asarray(idx).T), shape=tuple(shape))
    t = Tensor(mat.todense())
    return t
