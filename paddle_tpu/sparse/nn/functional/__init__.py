"""paddle.sparse.nn.functional — sparse conv/pool/activation/attention.

Reference: python/paddle/sparse/nn/functional/{conv.py,pooling.py,
activation.py,attention.py} over phi/kernels/sparse/ (gather-GEMM-scatter
rulebook convolution, ~35k LoC CUDA).

TPU-native formulation: the RULEBOOK (which input site feeds which output
site through which kernel offset) is built on host with vectorized numpy —
it is pure integer structure, data-independent of the values, and eager
construction keeps XLA shapes static. The VALUE computation (gather ->
per-offset GEMM -> scatter-add) runs on device through the op dispatch
chokepoint, so it lands on the autograd tape and grads flow to weights and
input values. Matmuls are [pairs, Cin] @ [Cin, Cout] — dense MXU work.
"""
from __future__ import annotations

import itertools

import numpy as np
import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....core.dispatch import apply_op, unwrap

__all__ = [
    "conv2d", "conv3d", "subm_conv2d", "subm_conv2d_igemm", "subm_conv3d",
    "subm_conv3d_igemm", "max_pool3d", "relu", "relu6", "leaky_relu",
    "softmax", "attention",
]


def _tuple(v, nd):
    if isinstance(v, (list, tuple)):
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(nd))
        assert len(v) == nd, f"expected {nd} values, got {v}"
        return tuple(int(x) for x in v)
    return (int(v),) * nd


def _encode(idx, dims):
    """[n, 1+nd] (batch, spatial...) -> flat int64 keys."""
    key = idx[:, 0].astype(np.int64)
    for a, d in enumerate(dims):
        key = key * int(d) + idx[:, a + 1].astype(np.int64)
    return key


def _rulebook(in_idx, spatial, kernel, stride, padding, dilation, subm):
    """Host-side rulebook construction.

    in_idx: np [nnz, 1+nd]; returns (out_idx [nnz_out, 1+nd],
    pairs: list over kernel offsets of (in_sel, out_sel) int32 arrays,
    out_spatial).
    """
    nd = len(spatial)
    offsets = list(itertools.product(*(range(k) for k in kernel)))
    if subm:
        if any(st != 1 for st in stride):
            raise ValueError(
                "submanifold conv requires stride=1 (output sites == input "
                "sites); use the regular sparse conv for strided downsampling")
        out_spatial = tuple(spatial)
        out_idx = in_idx
        keys = _encode(in_idx, out_spatial)
        order = np.argsort(keys)
        skeys = keys[order]
        pairs = []
        for off in offsets:
            # output site o takes input site o - padding + off * dilation
            # (centered kernels pass padding = (k-1)//2 * dilation)
            shift = np.array([off[a] * dilation[a] - padding[a]
                              for a in range(nd)], np.int64)
            cand = in_idx[:, 1:] + shift       # contributor coords per OUT site
            ok = np.all((cand >= 0) & (cand < np.array(spatial)), axis=1)
            cidx = np.concatenate([in_idx[:, :1], cand], axis=1)
            ckeys = _encode(cidx, out_spatial)
            pos = np.searchsorted(skeys, ckeys)
            pos = np.clip(pos, 0, len(skeys) - 1)
            hit = ok & (skeys[pos] == ckeys)
            in_sel = order[pos[hit]].astype(np.int32)   # contributor row
            out_sel = np.nonzero(hit)[0].astype(np.int32)
            pairs.append((in_sel, out_sel))
        return out_idx, pairs, out_spatial
    out_spatial = tuple(
        (spatial[a] + 2 * padding[a] - dilation[a] * (kernel[a] - 1) - 1)
        // stride[a] + 1 for a in range(nd))
    cand_idx, cand_off = [], []
    for ki, off in enumerate(offsets):
        # in = out*stride - pad + off*dil  =>  out = (in + pad - off*dil)/stride
        num = in_idx[:, 1:] + np.array(
            [padding[a] - off[a] * dilation[a] for a in range(nd)], np.int64)
        ok = np.all(num % np.array(stride) == 0, axis=1)
        out = num // np.array(stride)
        ok &= np.all((out >= 0) & (out < np.array(out_spatial)), axis=1)
        rows = np.nonzero(ok)[0].astype(np.int32)
        cand_idx.append((rows, np.concatenate(
            [in_idx[rows, :1], out[rows]], axis=1)))
    all_keys = np.concatenate(
        [_encode(c, out_spatial) for _, c in cand_idx]) \
        if cand_idx else np.zeros((0,), np.int64)
    ukeys = np.unique(all_keys)
    nnz_out = len(ukeys)
    out_idx = np.zeros((nnz_out, nd + 1), np.int64)
    rem = ukeys.copy()
    for a in range(nd - 1, -1, -1):
        out_idx[:, a + 1] = rem % out_spatial[a]
        rem //= out_spatial[a]
    out_idx[:, 0] = rem
    pairs = []
    for rows, cidx in cand_idx:
        ckeys = _encode(cidx, out_spatial)
        out_sel = np.searchsorted(ukeys, ckeys).astype(np.int32)
        pairs.append((rows, out_sel))
    return out_idx, pairs, out_spatial


def _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                 subm, nd, op_name):
    from ... import SparseCooTensor, sparse_coo_tensor
    if groups != 1:
        raise ValueError("sparse conv supports groups=1 (reference parity)")
    if not isinstance(x, SparseCooTensor):
        raise TypeError(f"{op_name} expects a SparseCooTensor input")
    w = weight if isinstance(weight, Tensor) else Tensor(jnp.asarray(weight))
    wshape = tuple(w.shape)                 # [*kernel, Cin, Cout]
    kernel = tuple(int(k) for k in wshape[:nd])
    cin, cout = int(wshape[nd]), int(wshape[nd + 1])
    stride = _tuple(stride, nd)
    padding = _tuple(padding, nd)
    dilation = _tuple(dilation, nd)
    shape = x.shape                         # [N, *spatial, C]
    spatial = tuple(int(s) for s in shape[1:1 + nd])
    if int(shape[-1]) != cin:
        raise ValueError(f"in_channels mismatch: x has {shape[-1]}, "
                         f"weight expects {cin}")
    in_idx = np.asarray(x.indices().numpy()).T      # [nnz, 1+nd]
    out_idx, pairs, out_spatial = _rulebook(
        in_idx, spatial, kernel, stride, padding, dilation, subm)
    nnz_out = len(out_idx)
    K = len(pairs)
    vals_t = x.values()
    dev_pairs = [(jnp.asarray(i), jnp.asarray(o)) for i, o in pairs]

    def f(vals, wk, *maybe_bias):
        w3 = wk.reshape(K, cin, cout)
        out = jnp.zeros((nnz_out, cout), vals.dtype)
        for k, (in_sel, out_sel) in enumerate(dev_pairs):
            if in_sel.shape[0] == 0:
                continue
            out = out.at[out_sel].add(
                vals[in_sel] @ w3[k].astype(vals.dtype))
        if maybe_bias:
            out = out + maybe_bias[0].astype(vals.dtype)
        return out

    args = (vals_t, w) + ((bias,) if bias is not None else ())
    out_vals = apply_op(op_name, f, *args)
    out_shape = (int(shape[0]),) + out_spatial + (cout,)
    return sparse_coo_tensor(out_idx.T, out_vals, out_shape,
                             stop_gradient=out_vals.stop_gradient)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Sparse 3D conv (reference sparse/nn/functional/conv.py conv3d);
    weight [kd, kh, kw, Cin, Cout], x [N, D, H, W, C] COO."""
    assert data_format == "NDHWC", "sparse conv3d supports NDHWC"
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        False, 3, "sparse_conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv: output sites == input sites (no dilation of the
    active set), the standard trick that keeps sparsity through deep nets."""
    assert data_format == "NDHWC", "subm_conv3d supports NDHWC"
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        True, 3, "sparse_subm_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    assert data_format == "NHWC", "sparse conv2d supports NHWC"
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        False, 2, "sparse_conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    assert data_format == "NHWC", "subm_conv2d supports NHWC"
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        True, 2, "sparse_subm_conv2d")


# igemm variants: same math; the reference's implicit-GEMM kernel choice is a
# CUDA scheduling detail — on TPU both route to the rulebook GEMM.
def subm_conv2d_igemm(*args, **kwargs):
    return subm_conv2d(*args, **kwargs)


def subm_conv3d_igemm(*args, **kwargs):
    return subm_conv3d(*args, **kwargs)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Sparse max pool (reference sparse/nn/functional/pooling.py)."""
    from ... import SparseCooTensor, sparse_coo_tensor
    assert data_format == "NDHWC", "sparse max_pool3d supports NDHWC"
    if ceil_mode:
        raise NotImplementedError("sparse max_pool3d: ceil_mode not supported")
    nd = 3
    kernel = _tuple(kernel_size, nd)
    stride = _tuple(stride if stride is not None else kernel_size, nd)
    padding = _tuple(padding, nd)
    shape = x.shape
    spatial = tuple(int(s) for s in shape[1:1 + nd])
    C = int(shape[-1])
    in_idx = np.asarray(x.indices().numpy()).T
    out_idx, pairs, out_spatial = _rulebook(
        in_idx, spatial, kernel, stride, padding, (1, 1, 1), False)
    nnz_out = len(out_idx)
    dev_pairs = [(jnp.asarray(i), jnp.asarray(o)) for i, o in pairs]

    def f(vals):
        out = jnp.full((nnz_out, C), -jnp.inf, vals.dtype)
        for in_sel, out_sel in dev_pairs:
            if in_sel.shape[0] == 0:
                continue
            out = out.at[out_sel].max(vals[in_sel])
        return out

    out_vals = apply_op("sparse_max_pool3d", f, x.values())
    out_shape = (int(shape[0]),) + out_spatial + (C,)
    return sparse_coo_tensor(out_idx.T, out_vals, out_shape,
                             stop_gradient=out_vals.stop_gradient)


def _value_unary(op_name, fn):
    def op(x, *args, **kwargs):
        kwargs.pop("name", None)
        from ... import SparseCooTensor, SparseCsrTensor, sparse_coo_tensor, \
            sparse_csr_tensor
        if isinstance(x, SparseCsrTensor):
            mat = x._mat
            out_vals = apply_op(op_name,
                                lambda v: fn(v, *args, **kwargs), x.values())
            return sparse_csr_tensor(mat.indptr, mat.indices, out_vals,
                                     tuple(mat.shape),
                                     stop_gradient=out_vals.stop_gradient)
        idx = np.asarray(x.indices().numpy())
        out_vals = apply_op(op_name,
                            lambda v: fn(v, *args, **kwargs), x.values())
        return sparse_coo_tensor(idx, out_vals, tuple(x.shape),
                                 stop_gradient=out_vals.stop_gradient)
    op.__name__ = op_name
    return op


relu = _value_unary("sparse_relu", jax.nn.relu)
relu6 = _value_unary("sparse_relu6", lambda v: jnp.clip(v, 0.0, 6.0))


def leaky_relu(x, negative_slope=0.01, name=None):
    return _value_unary(
        "sparse_leaky_relu",
        lambda v: jax.nn.leaky_relu(v, negative_slope))(x)


def softmax(x, axis=-1, name=None):
    """Per-row softmax over stored values (reference sparse softmax kernel:
    explicit zeros participate, absent entries don't). Segment ops over the
    CSR value array — never densifies."""
    from ... import SparseCooTensor, SparseCsrTensor, sparse_csr_tensor
    if axis != -1:
        raise ValueError("sparse softmax supports axis=-1 only (CSR rows)")
    was_coo = isinstance(x, SparseCooTensor)
    csr = x.to_sparse_csr() if was_coo else x
    mat = csr._mat
    if len(mat.shape) != 2:
        raise ValueError("sparse softmax expects a 2D tensor")
    nrows = mat.shape[0]
    indptr, cols = mat.indptr, mat.indices
    nse = mat.nse

    def f(vals):
        row = jnp.searchsorted(indptr, jnp.arange(nse), side="right") - 1
        rmax = jax.ops.segment_max(vals, row, num_segments=nrows)
        ex = jnp.exp(vals - rmax[row])
        denom = jax.ops.segment_sum(ex, row, num_segments=nrows)
        return ex / denom[row]

    out_vals = apply_op("sparse_softmax", f, csr.values())
    res = sparse_csr_tensor(indptr, cols, out_vals, tuple(mat.shape),
                            stop_gradient=out_vals.stop_gradient)
    return res.to_sparse_coo() if was_coo else res


def attention(query, key, value, sparse_mask, key_padding_mask=None,
              attn_mask=None, name=None):
    """Sparse-mask attention (reference sparse/nn/functional/attention.py:
    fused_attention over a CSR mask [B*H, S, S]): scores computed ONLY at the
    mask's nnz coordinates, per-row segment softmax, weighted gather-sum of V.

    query/key/value: [B, H, S, D] dense; sparse_mask: SparseCsrTensor with
    shape [B*H, S, S]. key_padding_mask/attn_mask: optional dense additive
    masks ([B, S] and [S, S])."""
    from ... import SparseCsrTensor, SparseCooTensor, _coo
    from jax.experimental import sparse as jsparse
    if not isinstance(sparse_mask, (SparseCsrTensor, SparseCooTensor)):
        raise TypeError("sparse_mask must be a sparse tensor")
    bco = _coo(sparse_mask)
    BH, S, S2 = (int(d) for d in bco.shape)
    if bco.n_batch:
        bco = jsparse.bcoo_update_layout(bco, n_batch=0,
                                         on_inefficient=None)
    midx = np.asarray(bco.indices)                   # [nnz, 3] (bh, i, j)
    rows_d = jnp.asarray(midx[:, 0].astype(np.int64) * S + midx[:, 1])
    cols_d = jnp.asarray(midx[:, 2].astype(np.int64))
    kpm = unwrap(key_padding_mask) if key_padding_mask is not None else None
    am = unwrap(attn_mask) if attn_mask is not None else None

    def f(q, k, v):
        B, H, Sq, D = q.shape
        qf = q.reshape(B * H * Sq, D)
        kf = k.reshape(B * H, Sq, D)
        vf = v.reshape(B * H, Sq, D)
        bh = rows_d // Sq
        qi = qf[rows_d]                              # [nnz, D]
        kj = kf[bh, cols_d]                          # [nnz, D]
        s = jnp.sum(qi.astype(jnp.float32) * kj.astype(jnp.float32),
                    axis=-1) / jnp.sqrt(jnp.float32(D))
        if kpm is not None:
            b = bh // H
            s = s + kpm[b, cols_d].astype(jnp.float32)
        if am is not None:
            i = rows_d % Sq
            s = s + am[i, cols_d].astype(jnp.float32)
        nrows = B * H * Sq
        rmax = jax.ops.segment_max(s, rows_d, num_segments=nrows)
        ex = jnp.exp(s - rmax[rows_d])
        denom = jax.ops.segment_sum(ex, rows_d, num_segments=nrows)
        p = (ex / jnp.maximum(denom[rows_d], 1e-30)).astype(v.dtype)
        contrib = p[:, None] * vf[bh, cols_d]
        out = jax.ops.segment_sum(contrib, rows_d, num_segments=nrows)
        return out.reshape(B, H, Sq, D).astype(v.dtype)

    return apply_op("sparse_attention", f, query, key, value)
