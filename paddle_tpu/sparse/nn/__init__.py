"""paddle.sparse.nn — sparse layers (reference: python/paddle/sparse/nn/layer/
{conv.py,pooling.py,norm.py,activation.py}).

Layers hold dense Parameters; forward routes through
paddle_tpu.sparse.nn.functional, so autograd flows from sparse outputs back
to the weights (and to input values) through the op dispatch tape.
"""
from __future__ import annotations

import numpy as np

from . import functional  # noqa: F401
from . import functional as F
from ...nn import Layer

__all__ = [
    "ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm", "SyncBatchNorm",
    "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D", "MaxPool3D",
]


def _ntuple(v, nd):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v),) * nd


class _SparseConv(Layer):
    _nd = 3
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=None, key=None,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None):
        super().__init__()
        nd = self._nd
        if padding_mode != "zeros":
            raise ValueError("sparse conv supports padding_mode='zeros'")
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, nd)
        self._stride = _ntuple(stride, nd)
        self._padding = padding
        self._dilation = _ntuple(dilation, nd)
        self._groups = groups
        self._key = key
        fan = int(np.prod(self._kernel_size)) * in_channels
        wshape = list(self._kernel_size) + [in_channels, out_channels]
        from ...nn.initializer import KaimingNormal, Constant
        self.weight = self.create_parameter(
            wshape, attr=weight_attr,
            default_initializer=KaimingNormal(fan_in=fan))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, x):
        fn = {
            (2, False): F.conv2d, (2, True): F.subm_conv2d,
            (3, False): F.conv3d, (3, True): F.subm_conv3d,
        }[(self._nd, self._subm)]
        kw = {"key": self._key} if self._subm else {}
        return fn(x, self.weight, self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation,
                  groups=self._groups, **kw)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, subm={self._subm}")


class Conv3D(_SparseConv):
    _nd, _subm = 3, False


class SubmConv3D(_SparseConv):
    _nd, _subm = 3, True


class Conv2D(_SparseConv):
    _nd, _subm = 2, False


class SubmConv2D(_SparseConv):
    _nd, _subm = 2, True


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        if return_mask:
            raise NotImplementedError("sparse MaxPool3D: return_mask")
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._ceil_mode = ceil_mode

    def forward(self, x):
        return F.max_pool3d(x, self._kernel_size, self._stride,
                            self._padding, self._ceil_mode)


class BatchNorm(Layer):
    """Sparse BatchNorm (reference sparse/nn/layer/norm.py BatchNorm):
    normalizes VALUES per channel over the active sites — exactly dense
    BatchNorm1D over the [nnz, C] value matrix."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ...nn import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr,
                               use_global_stats=use_global_stats)

    def forward(self, x):
        from .. import sparse_coo_tensor
        idx = np.asarray(x.indices().numpy())
        out_vals = self._bn(x.values())
        return sparse_coo_tensor(idx, out_vals, tuple(x.shape),
                                 stop_gradient=out_vals.stop_gradient)


class SyncBatchNorm(BatchNorm):
    """Cross-replica sparse BatchNorm. Under pjit/GSPMD the value matrix is
    globally visible to the compiler, so the dense batch statistics ARE the
    synchronized statistics — no explicit collective needed (reference needs
    NCCL all_reduce; SURVEY §7 maps this role to GSPMD)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(layer,
                                                           SyncBatchNorm):
            new = SyncBatchNorm(layer._bn._num_features)
            new._bn = layer._bn
            return new
        for name, sub in getattr(layer, "_sub_layers", {}).items():
            setattr(layer, name, cls.convert_sync_batchnorm(sub))
        return layer


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class ReLU6(Layer):
    def forward(self, x):
        return F.relu6(x)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)
