"""Span-scoped tracing bridging the metrics registry and the profiler.

One ``trace_span(name)`` emits, while observability is enabled:

1. a ``jax.profiler.TraceAnnotation`` — the span shows up in the XPlane /
   TensorBoard / Perfetto timeline whenever a device trace is recording,
2. a host-side event in ``paddle_tpu.profiler._host_events`` — the span rides
   the existing ``Profiler.export()`` chrome-trace path and the
   ``summary()`` user-event table with no extra plumbing, and
3. an observation in the ``span_seconds`` histogram (label ``span=<name>``).

Disabled, a span costs one flag check and a no-op context manager.
"""
from __future__ import annotations

import time
from contextlib import contextmanager

from . import registry as _registry

SPAN_SECONDS = _registry.REGISTRY.histogram(
    "span_seconds", "wall time inside trace_span scopes", ("span",))


@contextmanager
def trace_span(name: str):
    """Time a scope into the registry, the profiler, and the device trace."""
    if not _registry._ENABLED:
        yield
        return
    import jax
    from ..profiler import _host_events
    ann = jax.profiler.TraceAnnotation(name)
    ann.__enter__()
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        ann.__exit__(None, None, None)
        _host_events[name].append(dt)
        SPAN_SECONDS.labels(span=name).observe(dt)
