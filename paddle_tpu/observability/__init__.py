"""paddle_tpu.observability — unified runtime telemetry.

The reference ships a whole observability layer (python/paddle/profiler/
profiler.py:358: chrome-trace export, operator/memory summaries); this package
is its serving-era counterpart: ONE process-wide metrics registry plus span
tracing, threaded through dispatch, jit capture, the serving engine, and the
collective plane.

Usage::

    from paddle_tpu import observability as obs

    obs.enable()                      # flips the process-wide switch AND
                                      # installs the dispatch recorder
    ... run work ...
    snap = obs.snapshot()             # JSON-able dict
    text = obs.render_prometheus()    # Prometheus text exposition
    with obs.trace_span("my.phase"):  # TraceAnnotation + chrome-trace event
        ...
    obs.disable()

Cost model: disabled (the default), every instrumented call site pays one
global-bool check; the op-dispatch hot path pays nothing at all because
``enable()``/``disable()`` install/remove the recorder in core.dispatch's
single instrumentation slot (``bench.py``'s serving extra measures the
enabled-vs-disabled decode throughput to keep this claim honest).

Standard metric families are declared here, in one place, so instrumented
modules share names and label schemas instead of inventing their own.
"""
from __future__ import annotations

from . import flight  # noqa: F401  (request tracing / flight recorder)
from . import registry as _registry
from .registry import (DEFAULT_BUCKETS, REGISTRY, MetricsRegistry,  # noqa: F401
                       enabled, merge_snapshots, render_snapshot)
from .tracing import SPAN_SECONDS, trace_span  # noqa: F401

__all__ = [
    "MetricsRegistry", "REGISTRY", "DEFAULT_BUCKETS",
    "enable", "disable", "enabled", "reset",
    "snapshot", "render_prometheus", "render_snapshot", "merge_snapshots",
    "trace_span", "record_collective", "start_metrics_server", "flight",
]


def start_metrics_server(port: int = 0, addr: str = "127.0.0.1"):
    """Serve :func:`render_prometheus` at ``http://addr:port/metrics`` (the
    standard scrape interface); see :mod:`.exporter`.  Lazy so importing the
    package never pays for http.server."""
    from .exporter import start_metrics_server as _start
    return _start(port=port, addr=addr)

# ---- standard families -------------------------------------------------------
# dispatch (core/dispatch.py, fed through the op_recorder slot)
DISPATCH_OPS = REGISTRY.counter(
    "dispatch_ops_total", "ops dispatched through apply_op", ("op",))
DISPATCH_AUTOCAST = REGISTRY.counter(
    "dispatch_autocast_total", "dispatches with AMP autocast active")
DISPATCH_TAPED = REGISTRY.counter(
    "dispatch_taped_total", "dispatches that recorded a vjp tape node")
DISPATCH_LIFTS = REGISTRY.counter(
    "dispatch_trace_lifted_total",
    "dispatches under an active trace context (program-capture lifts)")
DISPATCH_SECONDS = REGISTRY.histogram(
    "dispatch_host_seconds", "host wall time per op dispatch")

# jit program capture (jit/to_static.py)
JIT_EVENTS = REGISTRY.counter(
    "jit_events_total",
    "to_static lifecycle events (capture/cache_hit/retrace/"
    "guard_divergence/eager_call/echo_mismatch)", ("event", "fn"))

# serving engine (inference/serving.py); one label per engine instance
SERVING_TTFT = REGISTRY.histogram(
    "serving_ttft_seconds", "submit-to-first-token latency", ("engine",))
SERVING_TOKEN_LATENCY = REGISTRY.histogram(
    "serving_token_latency_seconds",
    "per-token decode latency (dispatch wall / block size)", ("engine",))
SERVING_QUEUE_DEPTH = REGISTRY.gauge(
    "serving_queue_depth", "requests waiting for admission", ("engine",))
SERVING_ACTIVE_SLOTS = REGISTRY.gauge(
    "serving_active_slots", "slots holding an admitted request", ("engine",))
SERVING_OCCUPANCY = REGISTRY.gauge(
    "serving_batch_occupancy_ratio", "active slots / max_batch", ("engine",))
SERVING_DISPATCHES = REGISTRY.counter(
    "serving_dispatches_total", "engine programs dispatched",
    ("engine", "kind"))                        # kind: prefill | decode | verify
SERVING_TOKENS = REGISTRY.counter(
    "serving_generated_tokens_total", "tokens emitted to requests",
    ("engine",))
SERVING_PREEMPTIONS = REGISTRY.counter(
    "serving_preemptions_total", "slots preempted back to the queue",
    ("engine",))
SERVING_CACHE_EVENTS = REGISTRY.counter(
    "serving_prefix_cache_events_total",
    "prefix-cache page events (hit/miss/eviction/cow_copy)",
    ("engine", "event"))
SERVING_CACHED_PAGES = REGISTRY.gauge(
    "serving_prefix_cached_pages", "pages registered in the prefix index",
    ("engine",))
SERVING_RECLAIMABLE_PAGES = REGISTRY.gauge(
    "serving_prefix_reclaimable_pages",
    "cached-but-unreferenced pages parked in the LRU", ("engine",))
SERVING_FREE_PAGES = REGISTRY.gauge(
    "serving_free_pages", "pages on the free list", ("engine",))
SERVING_SPEC_PROPOSED = REGISTRY.counter(
    "serving_spec_proposed_total",
    "draft tokens proposed by speculative decoding", ("engine",))
SERVING_SPEC_ACCEPTED = REGISTRY.counter(
    "serving_spec_accepted_total",
    "draft tokens accepted by in-graph verification", ("engine",))
SERVING_SPEC_ACCEPTANCE = REGISTRY.histogram(
    "serving_spec_acceptance_ratio",
    "per-verify-step accepted/proposed draft ratio", ("engine",),
    buckets=(0.0, 0.25, 0.5, 0.75, 0.9, 1.0))

# KV-cache hierarchy (HBM -> host RAM -> peer replica -> recompute)
SERVING_KV_TIER_EVENTS = REGISTRY.counter(
    "serving_kv_tier_events_total",
    "KV tier page movements (spill/restore/peer_export/peer_import)",
    ("engine", "event"))
SERVING_KV_TIER_BYTES = REGISTRY.counter(
    "serving_kv_tier_bytes_total",
    "KV bytes moved between tiers, by direction "
    "(spill/restore/peer_out/peer_in)", ("engine", "direction"))
SERVING_KV_TIER_HITS = REGISTRY.counter(
    "serving_kv_tier_hits_total",
    "admission prefix-cache page hits by serving tier (hbm/host)",
    ("engine", "tier"))
SERVING_HOST_CACHED_PAGES = REGISTRY.gauge(
    "serving_host_cached_pages",
    "KV pages resident in the host-RAM spill tier", ("engine",))

# disaggregated prefill/decode (inference/engine/disagg.py); pool labels the
# DisaggEngine instance, path says how the KV block crossed the seam
SERVING_HANDOFF_QUEUE_DEPTH = REGISTRY.gauge(
    "serving_handoff_queue_depth",
    "prefill→decode handoffs waiting in the pool's bounded queue", ("pool",))
SERVING_HANDOFF_WAIT_SECONDS = REGISTRY.histogram(
    "serving_handoff_wait_seconds",
    "queue wait from prefill completion to transfer dispatch",
    ("pool", "path"),                          # path: local | cross_host
    buckets=(0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))
SERVING_HANDOFF_TRANSFER_SECONDS = REGISTRY.histogram(
    "serving_handoff_transfer_seconds",
    "wall time a KV handoff spent in transfer work the decode loop could "
    "not overlap (async: dispatch+land; sync: the whole blocking hop)",
    ("pool", "path"),
    buckets=(0.0001, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))

SERVING_TERMINALS = REGISTRY.counter(
    "serving_terminal_requests_total",
    "requests reaching a typed terminal status "
    "(finished/eos/timeout/cancelled/shed/failed)", ("engine", "status"))
SERVING_STEP_FAILURES = REGISTRY.counter(
    "serving_step_failures_total",
    "engine step dispatches that raised (pre-isolation)",
    ("engine", "phase"))                       # phase: prefill | decode | verify
SERVING_QUARANTINE_PROBES = REGISTRY.counter(
    "serving_quarantine_probes_total",
    "single-slot isolation probes dispatched after a batched-step failure",
    ("engine",))

# serving front door (inference/frontend/); replica labels name the engine
# replica a request was routed to, reason says why the router picked it
FRONTEND_REQUESTS = REGISTRY.counter(
    "frontend_requests_total",
    "gateway requests by terminal outcome "
    "(finished/eos/timeout/cancelled/shed/failed)", ("outcome",))
FRONTEND_ROUTED = REGISTRY.counter(
    "frontend_routed_total",
    "requests dispatched to a replica, by routing reason "
    "(affinity/least_loaded/round_robin)", ("replica", "reason"))
FRONTEND_AFFINITY = REGISTRY.counter(
    "frontend_affinity_events_total",
    "router prefix-affinity decisions (hit: scored prefix overlap won; "
    "miss: no replica held any prefix page)", ("event",))
FRONTEND_SHED = REGISTRY.counter(
    "frontend_shed_total",
    "requests rejected before reaching a replica, by admission reason",
    ("reason",))
FRONTEND_INFLIGHT = REGISTRY.gauge(
    "frontend_inflight_requests",
    "requests admitted by the gateway and not yet terminal")
FRONTEND_STREAM_SECONDS = REGISTRY.histogram(
    "frontend_stream_seconds",
    "submit-to-terminal wall time per gateway request")

# membership plane (distributed/membership.py); group labels the fleet
MEMBERSHIP_LEASE_EXPIRIES = REGISTRY.counter(
    "membership_lease_expiries_total",
    "member leases a watcher declared expired (missed heartbeats)",
    ("group",))
MEMBERSHIP_EVENTS = REGISTRY.counter(
    "membership_events_total",
    "membership transitions observed by watchers (join/leave/expire)",
    ("group", "kind"))
MEMBERSHIP_HEARTBEAT_SECONDS = REGISTRY.histogram(
    "membership_heartbeat_seconds",
    "wall time of one lease renewal (store round-trip incl. retries)",
    ("group",),
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))

# self-healing fleet (inference/frontend/ supervisor + requeue path)
FRONTEND_RESTARTS = REGISTRY.counter(
    "frontend_replica_restarts_total",
    "worker processes respawned by the supervisor after a crash",
    ("replica",))
FRONTEND_QUARANTINES = REGISTRY.counter(
    "frontend_replica_quarantines_total",
    "replicas the crash-loop circuit breaker stopped respawning (alert!)",
    ("replica",))
FRONTEND_REQUEUED = REGISTRY.counter(
    "frontend_requeued_total",
    "inflight requests transparently re-enqueued onto a surviving replica "
    "after their replica died before streaming any token")
FRONTEND_RESUMED = REGISTRY.counter(
    "frontend_resumed_total",
    "partially-streamed requests resumed token-exact on a surviving "
    "replica (re-prefill of prompt + emitted history) after their replica "
    "died mid-stream")
FRONTEND_SPLICE_SECONDS = REGISTRY.histogram(
    "frontend_resume_splice_seconds",
    "replica-death detection to the first post-resume token — the stall a "
    "streaming client rides through a crash",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0))
FRONTEND_STUCK_STEPS = REGISTRY.counter(
    "frontend_stuck_steps_total",
    "replica steps the wall-clock watchdog declared wedged (gray failure "
    "promoted to a typed replica death)", ("replica",))
FRONTEND_PEER_PULLS = REGISTRY.counter(
    "frontend_peer_pulls_total",
    "peer-replica KV page pulls before prefill, by outcome "
    "(ok: pages spliced; miss: holder no longer had the chain; "
    "failed: RPC/fault — recompute fallback)", ("outcome",))

# metrics federation (gateway /metrics scraping live fleet members)
FRONTEND_FEDERATION_ERRORS = REGISTRY.counter(
    "frontend_federation_errors_total",
    "fleet members whose metrics/trace scrape FAILED (wedged past the "
    "scrape deadline, or died mid-scrape); members already known dead are "
    "not re-counted per scrape", ("replica",))
FRONTEND_FEDERATION_SKIPPED = REGISTRY.gauge(
    "frontend_federation_skipped",
    "fleet members skipped on the last federation scrape because they "
    "were already known dead (their failure was counted once, when "
    "detected)")

# durable request plane (inference/frontend/journal.py + gateway)
JOURNAL_APPEND_SECONDS = REGISTRY.histogram(
    "journal_append_seconds",
    "wall time of one request-journal append (incl. any fsync)",
    buckets=(0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5))
JOURNAL_REPLAYED = REGISTRY.counter(
    "journal_replayed_total",
    "journal records consumed during crash recovery, by record kind "
    "(accepted/tokens/terminal/result)", ("kind",))
GATEWAY_RECOVERIES = REGISTRY.counter(
    "gateway_recoveries_total",
    "gateway restarts that replayed a non-empty request journal")
STREAM_REATTACH = REGISTRY.counter(
    "stream_reattach_total",
    "SSE clients that reconnected with Last-Event-ID and were spliced "
    "back onto a journaled stream")

# shared retry helper (core/retry.py); op labels the retried operation
RETRY_ATTEMPTS = REGISTRY.histogram(
    "retry_attempts", "attempts consumed per retried operation", ("op",),
    buckets=(1.0, 2.0, 3.0, 5.0, 8.0, 13.0))
RETRY_EXHAUSTED = REGISTRY.counter(
    "retry_exhausted_total", "retried operations that ran out of attempts",
    ("op",))

# collective watchdog (distributed/watchdog.py)
COMM_WATCHDOG_TIMEOUTS = REGISTRY.counter(
    "comm_watchdog_timeouts_total",
    "collectives the watchdog declared timed out (probable hangs)", ("op",))

# collective plane (distributed/collective.py + parallel/ layers)
COLLECTIVE_CALLS = REGISTRY.counter(
    "collective_invocations_total",
    "explicit eager collectives invoked", ("collective",))
COLLECTIVE_BYTES = REGISTRY.counter(
    "collective_payload_bytes_total",
    "payload bytes moved by explicit eager collectives", ("collective",))
COLLECTIVE_TRACED = REGISTRY.counter(
    "collective_traced_total",
    "in-mesh collectives captured at trace time (ticks once per compiled "
    "program, not per device execution)", ("collective",))
COLLECTIVE_TRACED_BYTES = REGISTRY.counter(
    "collective_traced_payload_bytes_total",
    "per-shard payload bytes of traced in-mesh collectives", ("collective",))


# ---- dispatch recorder -------------------------------------------------------
class _DispatchRecorder:
    """Lives in core.dispatch's single ``op_recorder`` slot while metrics are
    on (composed with the profiler's HostOpRecorder when both are active), so
    apply_op keeps exactly one instrumentation branch."""

    __slots__ = ()

    def record(self, name, dt, amp=False, taped=False, lifted=False):
        DISPATCH_OPS.inc(op=name)
        DISPATCH_SECONDS.observe(dt)
        if amp:
            DISPATCH_AUTOCAST.inc()
        if taped:
            DISPATCH_TAPED.inc()
        if lifted:
            DISPATCH_LIFTS.inc()


_DISPATCH_RECORDER = _DispatchRecorder()


def enable() -> None:
    """Flip the process-wide telemetry switch on and install the dispatch
    recorder (threads pick it up on their next dispatch-state access)."""
    from ..core import dispatch as _dispatch
    _registry._set_enabled(True)
    _dispatch.set_metrics_recorder(_DISPATCH_RECORDER)


def disable() -> None:
    """Switch telemetry off; dispatch returns to its zero-cost fast path."""
    from ..core import dispatch as _dispatch
    _dispatch.set_metrics_recorder(None)
    _registry._set_enabled(False)


def reset() -> None:
    """Zero every series in place (bound children stay valid); the
    enable/disable switch is left untouched."""
    REGISTRY.reset()


def snapshot(prefix=None, labels=None) -> dict:
    """JSON-able dump of the default registry (see
    :meth:`MetricsRegistry.snapshot` for the filters)."""
    return REGISTRY.snapshot(prefix=prefix, labels=labels)


def render_prometheus() -> str:
    """Prometheus text exposition of the default registry."""
    return REGISTRY.render_prometheus()


# (shape, dtype) -> XLA-measured payload bytes; None caches a probe failure
# so an environment without cost analysis pays the attempt exactly once
_XLA_BYTES_CACHE: dict = {}


def _xla_payload_bytes(payload):
    """Payload bytes as XLA's cost analysis measures them, or None when the
    payload is a tracer / not a jax.Array / the backend exposes no cost
    model.  A trivial elementwise program is lowered per (shape, dtype) —
    identity alone can be optimized to a parameter pass-through that
    reports zero — and the operand's 'bytes accessed' is read off the
    compiled executable; results are cached so each distinct payload shape
    compiles the probe once."""
    try:
        import jax
    except ImportError:  # no jax, no cost model
        return None
    if not isinstance(payload, jax.Array) \
            or isinstance(payload, jax.core.Tracer):
        return None
    try:
        key = (payload.shape, str(payload.dtype))
    except (AttributeError, TypeError):
        return None
    if key in _XLA_BYTES_CACHE:
        return _XLA_BYTES_CACHE[key]
    nbytes = None
    try:
        cost = (jax.jit(lambda a: a * 1).lower(payload).compile()
                .cost_analysis())
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if cost:
            # operand 0's bytes are exactly the payload; fall back to the
            # output's, then to half the total (in + out) access bytes
            for k in ("bytes accessed0{}", "bytes accessedout{}"):
                if cost.get(k):
                    nbytes = int(cost[k])
                    break
            else:
                total = cost.get("bytes accessed")
                nbytes = int(total) // 2 if total else None
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        nbytes = None
    _XLA_BYTES_CACHE[key] = nbytes
    return nbytes


def record_collective(name, payload=None, traced=True, nbytes=None) -> None:
    """Count one collective invocation, with payload bytes when derivable.

    traced=True: the call site sits inside a traced program (shard_map body),
    so the count ticks once per trace and bytes are the per-shard aval size.
    ``payload`` may be an array/tracer or None; pass ``nbytes`` to override.
    Bytes come from XLA's cost analysis when the payload is a concrete
    on-device array (what the hardware actually moves, including any layout
    padding); tracers and off-device values fall back to the aval-derived
    ``size * itemsize``.
    """
    if not _registry._ENABLED:
        return
    calls, by = ((COLLECTIVE_TRACED, COLLECTIVE_TRACED_BYTES) if traced
                 else (COLLECTIVE_CALLS, COLLECTIVE_BYTES))
    calls.inc(collective=name)
    if nbytes is None and payload is not None:
        nbytes = _xla_payload_bytes(payload)
        if nbytes is None:
            try:
                nbytes = int(payload.size) * payload.dtype.itemsize
            except (AttributeError, TypeError):
                nbytes = None
    if nbytes:
        by.inc(int(nbytes), collective=name)
