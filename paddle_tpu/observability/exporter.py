"""Prometheus pull endpoint — the minimal ``/metrics`` HTTP server.

The registry renders text exposition on demand (:func:`render_prometheus`);
this module puts it behind the standard scrape interface so a Prometheus (or
curl) can pull it without the serving loop doing any push-side work.  Pure
stdlib, daemon-threaded, and zero-cost to the engine: each scrape renders the
registry on the handler thread.

Usage::

    from paddle_tpu import observability as obs

    obs.enable()
    server = obs.start_metrics_server(port=9400)   # port=0 -> OS-assigned
    print(server.url)                              # http://127.0.0.1:9400/metrics
    ...
    server.close()
"""
from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class MetricsServer:
    """Handle on a running exporter: ``addr``/``port``/``url`` + ``close()``."""

    def __init__(self, httpd: ThreadingHTTPServer, thread: threading.Thread):
        self._httpd = httpd
        self._thread = thread
        self.addr, self.port = httpd.server_address[:2]
        self.url = f"http://{self.addr}:{self.port}/metrics"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _Handler(BaseHTTPRequestHandler):
    # text exposition format version per the Prometheus spec
    _CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def do_GET(self):  # noqa: N802 (stdlib handler API)
        if self.path.split("?")[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        from . import render_prometheus
        body = render_prometheus().encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", self._CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):    # scrapes are not log events
        pass


def start_metrics_server(port: int = 0,
                         addr: str = "127.0.0.1") -> MetricsServer:
    """Serve the registry at ``http://addr:port/metrics`` from a daemon
    thread; ``port=0`` lets the OS pick (read it back from the returned
    handle).  The caller owns the handle: ``close()`` stops the server."""
    httpd = ThreadingHTTPServer((addr, port), _Handler)
    httpd.daemon_threads = True
    thread = threading.Thread(target=httpd.serve_forever,
                              name="paddle-tpu-metrics", daemon=True)
    thread.start()
    return MetricsServer(httpd, thread)
