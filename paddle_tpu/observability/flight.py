"""Cross-process request tracing: trace contexts, a bounded per-process
flight recorder, and chrome-trace export/merge.

The metrics registry answers "how is the fleet doing"; this module answers
"where did request X spend its time" after that request crossed three
process boundaries (gateway -> RPC worker -> disagg pool).  Three pieces:

- :class:`TraceContext` — a trace id plus a Lamport clock stamp.  The
  gateway mints one per request (or adopts the client's ``X-Request-ID``);
  it crosses the worker RPC plane as a tiny picklable tuple
  (:func:`wire_context` / :func:`adopt_wire`), and inside a process it
  travels ambiently in a :mod:`contextvars` variable so deep call stacks
  (``gateway -> ReplicaSet.submit -> engine.add_request``) never need a
  threaded-through parameter.  The clock is process-global and ticks on
  every recorded event; a receiver folds the sender's stamp in with
  ``max(local, received) + 1``, so event ``lamport`` values are monotone
  along every causal chain even though processes share no wall clock.

- the flight recorder — a bounded ring (``deque(maxlen=...)``) of span
  events.  Disabled (the default) every :func:`record` call returns after
  one module-global flag check; enabled, an event is a small dict appended
  under one lock.  :func:`pin` copies a trace's events into a non-evictable
  store — anomaly paths (stuck step, quarantine, resume, handoff poison)
  pin their victim so the evidence survives ring churn — and, when a dump
  directory is configured (``PADDLE_TPU_TRACE_DUMP_DIR`` or
  :func:`configure`), writes the pinned trace as a chrome-trace JSON file
  via the journal's atomic tmp + ``os.replace`` idiom.

- export/merge — :func:`merge_events` orders events from any number of
  process-local recorders by Lamport stamp, and :func:`chrome_trace`
  renders the merged list as a ``chrome://tracing`` / Perfetto-loadable
  JSON object (one chrome "process" per recorder label, with
  ``process_name`` metadata events).

Events are plain dicts so a worker can ship them over the RPC plane
(``trace_events`` op) with no extra serialization support.
"""
from __future__ import annotations

import contextvars
import hashlib
import json
import os
import re
import threading
import time
import uuid
from collections import deque

__all__ = [
    "TraceContext", "mint", "current", "use_context", "wire_context",
    "adopt_wire", "set_proc_label", "enable", "disable", "enabled",
    "configure", "record", "pin", "pin_rid", "events_for", "trace_for_rid",
    "snapshot_events", "pinned", "merge_events", "chrome_trace",
    "dump_trace", "reset",
]

_ENABLED = False

# process-global Lamport clock: ticks on every recorded event and on every
# context send/receive, folds received stamps in with max()+1
_clock_lock = threading.Lock()
_clock = 0


def _tick() -> int:
    global _clock
    with _clock_lock:
        _clock += 1
        return _clock


def _adopt(received: int) -> int:
    global _clock
    with _clock_lock:
        if received > _clock:
            _clock = received
        _clock += 1
        return _clock


class TraceContext:
    """One request's trace identity: the trace id plus the Lamport stamp it
    last crossed a boundary with.  Cheap, immutable-ish, picklable."""

    __slots__ = ("trace_id", "clock")

    def __init__(self, trace_id, clock=0):
        self.trace_id = str(trace_id)
        self.clock = int(clock)

    def __getstate__(self):
        return (self.trace_id, self.clock)

    def __setstate__(self, state):
        self.trace_id, self.clock = state

    def __repr__(self):
        return f"TraceContext({self.trace_id!r}, clock={self.clock})"


_current: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_trace_ctx", default=None)
# per-thread/process display label for recorded events ("gateway", worker
# name, ...); contextvar so threaded test fleets get distinct labels
_proc_label: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_trace_proc", default=None)


# Trace ids become dump filenames (``trace-<id>.json`` under the dump dir),
# and the gateway adopts the client-supplied X-Request-ID as the id — so a
# hostile header must never smuggle path syntax into os.replace.
_SAFE_ID = re.compile(r"[A-Za-z0-9._-]{1,120}")


def _safe_trace_id(trace_id) -> str:
    """Allowlisted id verbatim; anything else (path separators, overlong,
    control bytes) is replaced by a stable hash of itself, so a hostile
    client still gets a usable — and still collision-resistant — trace id."""
    tid = str(trace_id)
    if _SAFE_ID.fullmatch(tid):
        return tid
    digest = hashlib.sha256(tid.encode("utf-8", "surrogatepass")).hexdigest()
    return f"h{digest[:16]}"


def mint(trace_id=None) -> TraceContext:
    """New context: adopt the caller-supplied id (``X-Request-ID``),
    sanitized for filesystem safety, or mint a fresh one."""
    if not trace_id:
        return TraceContext(uuid.uuid4().hex[:16], _tick())
    return TraceContext(_safe_trace_id(trace_id), _tick())


def current():
    """The ambient :class:`TraceContext`, or None outside a traced scope."""
    return _current.get()


class use_context:
    """Install ``ctx`` as the ambient trace context for a scope (``with
    use_context(ctx): ...``).  ``ctx=None`` is a no-op passthrough."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        if self._ctx is not None:
            self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        if self._token is not None:
            _current.reset(self._token)
        return False


def wire_context():
    """The ambient context as a picklable ``(trace_id, clock)`` tuple for an
    RPC frame, ticking the clock (a send is an event) — or None when there
    is nothing to propagate."""
    ctx = _current.get()
    if ctx is None or not _ENABLED:
        return None
    return (ctx.trace_id, _tick())


def adopt_wire(wire):
    """Receiver half: fold the sender's Lamport stamp into the local clock
    and return a local :class:`TraceContext` (None for a None wire)."""
    if wire is None:
        return None
    trace_id, clock = wire
    return TraceContext(trace_id, _adopt(int(clock)))


def set_proc_label(label):
    """Name this thread's recorder events (worker name, "gateway", ...).
    Falls back to ``pid<os.getpid()>`` when never set."""
    _proc_label.set(str(label))


# --------------------------------------------------------- flight recorder
_DEFAULT_RING = 4096
_ring_lock = threading.Lock()
_ring: deque = deque(maxlen=_DEFAULT_RING)
_pinned: dict = {}            # trace_id -> {"reason", "events": [...]}
_PINNED_MAX = 256             # oldest pin evicted past this (anomaly churn)
_rid_to_trace: dict = {}      # rid -> trace_id (bounded, insertion order)
_RID_MAP_MAX = 4096
_dump_dir = None              # configure() override; else env var


def enable() -> None:
    """Switch the flight recorder on (independent of the metrics switch, so
    the bench can pin trace overhead on its own)."""
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def configure(ring_size=None, dump_dir=None) -> None:
    """Resize the ring (evicting from the old head) and/or set the anomaly
    dump directory (overrides ``PADDLE_TPU_TRACE_DUMP_DIR``)."""
    global _ring, _dump_dir
    if ring_size is not None:
        with _ring_lock:
            _ring = deque(_ring, maxlen=int(ring_size))
    if dump_dir is not None:
        _dump_dir = str(dump_dir)


def reset() -> None:
    """Drop every event, pin, and rid mapping (test isolation); the
    enable/disable switch and the Lamport clock are left untouched."""
    with _ring_lock:
        _ring.clear()
        _pinned.clear()
        _rid_to_trace.clear()


def record(phase, rid=None, trace_id=None, dur=None, **args) -> None:
    """Append one span event.  Disabled: one flag check.  Untraced (no
    explicit ``trace_id`` and no ambient context): a no-op — only requests
    that entered through a traced front door generate events."""
    if not _ENABLED:
        return
    if trace_id is None:
        ctx = _current.get()
        if ctx is None:
            return
        trace_id = ctx.trace_id
    ev = {
        "trace_id": trace_id,
        "phase": str(phase),
        "lamport": _tick(),
        # genuine wall clock: events from DIFFERENT processes merge on one
        # timeline, so the only shared clock is calendar time (causal order
        # still comes from the Lamport stamp, never from ts)
        "ts": time.time(),  # graftlint: disable=no-adhoc-telemetry
        "proc": _proc_label.get() or f"pid{os.getpid()}",
        "pid": os.getpid(),
    }
    if rid is not None:
        ev["rid"] = rid
    if dur is not None:
        ev["dur"] = float(dur)
    if args:
        ev["args"] = args
    with _ring_lock:
        _ring.append(ev)
        if rid is not None:
            if len(_rid_to_trace) >= _RID_MAP_MAX and rid not in _rid_to_trace:
                _rid_to_trace.pop(next(iter(_rid_to_trace)))
            _rid_to_trace[rid] = trace_id


def trace_for_rid(rid):
    """The trace id last recorded for ``rid`` in this process, or None."""
    return _rid_to_trace.get(rid)


def events_for(trace_id):
    """All events for one trace held in this process: pinned copy (if any)
    merged with whatever still lives in the ring, deduped by stamp."""
    with _ring_lock:
        ring = [e for e in _ring if e["trace_id"] == trace_id]
        pin = _pinned.get(trace_id)
        events = list(pin["events"]) if pin else []
    seen = {(e["lamport"], e["pid"]) for e in events}
    events += [e for e in ring if (e["lamport"], e["pid"]) not in seen]
    events.sort(key=lambda e: e["lamport"])
    return events


def snapshot_events(trace_id=None):
    """Picklable event list for the RPC pull: one trace's events, or (with
    ``trace_id=None``) the whole ring plus every pinned trace."""
    if trace_id is not None:
        return events_for(trace_id)
    with _ring_lock:
        events = list(_ring)
        extra = [e for pin in _pinned.values() for e in pin["events"]]
    seen = {(e["lamport"], e["pid"]) for e in events}
    events += [e for e in extra if (e["lamport"], e["pid"]) not in seen]
    events.sort(key=lambda e: e["lamport"])
    return events


def pinned():
    """{trace_id: reason} for every pinned trace in this process."""
    with _ring_lock:
        return {tid: pin["reason"] for tid, pin in _pinned.items()}


def pin(trace_id, reason) -> bool:
    """Copy a trace's events into the non-evictable store (anomaly capture)
    and, when a dump directory is configured, write the chrome-trace dump.
    Lock-ordering-safe from anywhere: takes only the recorder lock."""
    if not _ENABLED or trace_id is None:
        return False
    record("pinned", trace_id=trace_id, reason=str(reason))
    events = events_for(trace_id)
    with _ring_lock:
        # bounded like _rid_to_trace: replica churn pins every resumed
        # request, and a long-lived process must not leak anomaly captures —
        # past the cap the oldest pin falls out (its dump file, if any,
        # already made it to disk)
        if trace_id not in _pinned and len(_pinned) >= _PINNED_MAX:
            _pinned.pop(next(iter(_pinned)))
        _pinned[trace_id] = {"reason": str(reason), "events": events}
    d = _dump_dir or os.environ.get("PADDLE_TPU_TRACE_DUMP_DIR")
    if d:
        try:
            dump_trace(trace_id, events, reason=reason, out_dir=d)
        except OSError:
            pass             # post-mortem capture must never hurt serving
    return True


def pin_rid(rid, reason) -> bool:
    """Pin by engine/gateway request id (anomaly sites know the rid; the
    recorder remembers which trace it belonged to)."""
    return pin(_rid_to_trace.get(rid), reason)


def dump_trace(trace_id, events, reason=None, out_dir=None) -> str:
    """Write one trace as chrome-trace JSON, atomically (tmp +
    ``os.replace``, the journal idiom): readers never see a torn file, and
    a re-pin of the same trace replaces the dump in place."""
    d = out_dir or _dump_dir or os.environ.get("PADDLE_TPU_TRACE_DUMP_DIR")
    if not d:
        raise OSError("no trace dump directory configured")
    # mint() sanitizes every adopted id, but this is the write site: refuse
    # any id that could escape the dump dir rather than trust every caller
    if not _SAFE_ID.fullmatch(str(trace_id)):
        raise OSError(f"unsafe trace id for dump: {str(trace_id)!r}")
    os.makedirs(d, exist_ok=True)
    doc = chrome_trace(events)
    if reason is not None:
        doc["metadata"] = {"trace_id": trace_id, "pin_reason": str(reason)}
    path = os.path.join(d, f"trace-{trace_id}.json")
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# ----------------------------------------------------------- export / merge
def merge_events(*event_lists):
    """Fold per-process event lists (local recorder + ``trace_events`` RPC
    pulls) into ONE causally-ordered list: dedup by (lamport, pid) — the
    same event can arrive via both the ring and a pinned copy — then sort
    by Lamport stamp, wall time breaking ties between concurrent events."""
    seen = set()
    merged = []
    for events in event_lists:
        for e in events or ():
            key = (e["lamport"], e.get("pid"), e.get("proc"))
            if key in seen:
                continue
            seen.add(key)
            merged.append(e)
    merged.sort(key=lambda e: (e["lamport"], e.get("ts", 0.0)))
    return merged


def chrome_trace(events) -> dict:
    """Render events as a chrome://tracing / Perfetto JSON object.  One
    chrome "process" per recorder label (named via ``process_name``
    metadata events); spans with a duration become complete events
    (``ph="X"``), the rest instants (``ph="i"``)."""
    procs = {}
    trace_events = []
    for e in events:
        label = e.get("proc", "?")
        pid = procs.get(label)
        if pid is None:
            pid = procs[label] = len(procs) + 1
            trace_events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": label}})
        ev = {
            "name": e["phase"],
            "cat": "serving",
            "pid": pid,
            "tid": int(e.get("rid", 0)) if str(e.get("rid", 0)).isdigit()
                   else 0,
            "ts": round(e.get("ts", 0.0) * 1e6, 3),
            "args": {k: v for k, v in e.items()
                     if k not in ("phase", "ts", "dur", "proc")},
        }
        dur = e.get("dur")
        if dur is not None:
            ev["ph"] = "X"
            ev["dur"] = round(float(dur) * 1e6, 3)
            # chrome renders complete events from their START; recorded ts
            # is the span end (measured after the work), so rebase
            ev["ts"] = round(max(0.0, e.get("ts", 0.0) - float(dur)) * 1e6, 3)
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        trace_events.append(ev)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}
