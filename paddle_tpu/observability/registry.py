"""Process-wide metrics registry — Counter / Gauge / Histogram families with
label sets.

The reference builds its operator/memory summary tables post-hoc from profiler
records (python/paddle/profiler/profiler_statistic.py); a serving runtime needs
the same aggregates LIVE (TTFT distributions, queue depth, retrace storms), so
this module keeps them as mutable families that render to a JSON snapshot or
Prometheus text exposition on demand.

Design rules:

- One process-wide switch (:func:`enable` / :func:`disable`). Every mutation
  checks it first, so an instrumented binary with metrics off pays one module
  global read + one branch per call site — and the dispatch hot path pays
  NOTHING, because core/dispatch.py only carries a recorder in its single
  instrumentation slot while metrics are on.
- A family is created once (``registry.counter(name, help, labelnames)``) and
  cached by name; re-creating with a different type or label set is an error.
  Children ("series") are keyed by label values; hot call sites bind a child
  once (``family.labels(engine="0")``) and call ``.inc()/.observe()`` on it.
- Correctness under threads comes from a per-family lock around every
  read-modify-write (incrementing a Python float under the GIL alone is NOT
  atomic), taken only while metrics are enabled.
- :meth:`MetricsRegistry.reset` zeroes values IN PLACE: children bound before
  a reset stay valid, so test isolation never invalidates live handles.
"""
from __future__ import annotations

import bisect
import re
import threading

__all__ = ["MetricsRegistry", "REGISTRY", "enabled", "DEFAULT_BUCKETS",
           "render_snapshot", "merge_snapshots"]

_ENABLED = False


def enabled() -> bool:
    """Is the process-wide telemetry switch on?"""
    return _ENABLED


def _set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")

# Prometheus client defaults, extended downward: dispatch/token latencies on a
# local runtime sit well under a millisecond.
DEFAULT_BUCKETS = (
    1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2, 5e-2,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


def _escape_label(v: str) -> str:
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _escape_help(v: str) -> str:
    # HELP text escapes only backslash and newline (0.0.4 exposition spec)
    return v.replace("\\", r"\\").replace("\n", r"\n")


def _fmt(v) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


class _Family:
    """Base: a named metric with a fixed label schema and one lock."""

    kind = ""

    def __init__(self, name, help="", labelnames=()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}

    def labels(self, **labelvalues):
        """Bind (and memoize) the child for one label-value set."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._series.get(key)
            if child is None:
                child = self._series[key] = self._child_cls(self._lock)
        return child

    def _snapshot(self):
        with self._lock:
            return {
                "type": self.kind,
                "help": self.help,
                "series": [
                    {"labels": dict(zip(self.labelnames, key)),
                     **child._data()}
                    for key, child in sorted(self._series.items())
                ],
            }

    def _reset(self):
        with self._lock:
            for child in self._series.values():
                child._zero()


class _CounterChild:
    __slots__ = ("_lock", "value")

    def __init__(self, lock):
        self._lock = lock
        self.value = 0.0

    def inc(self, n=1):
        if not _ENABLED:
            return
        if n < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self.value += n

    def _data(self):
        return {"value": self.value}

    def _zero(self):
        self.value = 0.0


class _GaugeChild:
    __slots__ = ("_lock", "value")

    def __init__(self, lock):
        self._lock = lock
        self.value = 0.0

    def set(self, v):
        if not _ENABLED:
            return
        with self._lock:
            self.value = float(v)

    def inc(self, n=1):
        if not _ENABLED:
            return
        with self._lock:
            self.value += n

    def dec(self, n=1):
        self.inc(-n)

    def _data(self):
        return {"value": self.value}

    def _zero(self):
        self.value = 0.0


class _HistogramChild:
    __slots__ = ("_lock", "bounds", "counts", "sum", "count")

    # bounds injected per-family by HistogramFamily.labels (slot shared setup)
    def __init__(self, lock, bounds=()):
        self._lock = lock
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # last slot = +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, v):
        if not _ENABLED:
            return
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)   # le bounds are inclusive
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def _data(self):
        # raw (non-cumulative) per-bucket counts; rendering cumulates
        return {"buckets": dict(zip([*map(_fmt, self.bounds), "+Inf"],
                                    self.counts)),
                "sum": self.sum, "count": self.count}

    def _zero(self):
        self.counts = [0] * len(self.counts)
        self.sum = 0.0
        self.count = 0


class CounterFamily(_Family):
    kind = "counter"
    _child_cls = _CounterChild

    def inc(self, n=1, **labelvalues):
        if not _ENABLED:
            return
        self.labels(**labelvalues).inc(n)


class GaugeFamily(_Family):
    kind = "gauge"
    _child_cls = _GaugeChild

    def set(self, v, **labelvalues):
        if not _ENABLED:
            return
        self.labels(**labelvalues).set(v)

    def inc(self, n=1, **labelvalues):
        if not _ENABLED:
            return
        self.labels(**labelvalues).inc(n)


class HistogramFamily(_Family):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        b = tuple(sorted(float(x) for x in buckets))
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"{name}: buckets must be distinct and sorted")
        self.buckets = b

    def _child_cls(self, lock):
        return _HistogramChild(lock, self.buckets)

    def observe(self, v, **labelvalues):
        if not _ENABLED:
            return
        self.labels(**labelvalues).observe(v)


class MetricsRegistry:
    """Name -> family map with snapshot / Prometheus rendering."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _family(self, cls, name, help, labelnames, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = cls(name, help, labelnames, **kw)
            elif type(fam) is not cls or fam.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} re-registered as {cls.kind} with labels "
                    f"{tuple(labelnames)}; existing: {fam.kind} "
                    f"{fam.labelnames}")
            return fam

    def counter(self, name, help="", labelnames=()) -> CounterFamily:
        return self._family(CounterFamily, name, help, labelnames)

    def gauge(self, name, help="", labelnames=()) -> GaugeFamily:
        return self._family(GaugeFamily, name, help, labelnames)

    def histogram(self, name, help="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> HistogramFamily:
        return self._family(HistogramFamily, name, help, labelnames,
                            buckets=buckets)

    def snapshot(self, prefix=None, labels=None) -> dict:
        """JSON-able dump: {metric: {type, help, series: [{labels, ...}]}}.

        prefix: keep only metric names starting with it.
        labels: keep only series whose label dict CONTAINS these pairs.
        """
        with self._lock:
            fams = sorted(self._families.items())
        out = {}
        for name, fam in fams:
            if prefix and not name.startswith(prefix):
                continue
            snap = fam._snapshot()
            if labels:
                snap["series"] = [
                    s for s in snap["series"]
                    if all(s["labels"].get(k) == str(v)
                           for k, v in labels.items())]
            out[name] = snap
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of every family."""
        return render_snapshot(self.snapshot())

    def reset(self):
        """Zero every series in place (live children stay bound)."""
        with self._lock:
            fams = list(self._families.values())
        for fam in fams:
            fam._reset()


def render_snapshot(snap: dict) -> str:
    """Prometheus text exposition (version 0.0.4) of a snapshot dict — the
    single render path for both a live registry and federated (merged)
    remote snapshots, so escaping and histogram framing can't drift."""
    lines = []
    for name, fam in snap.items():
        if fam["help"]:
            lines.append(f"# HELP {name} {_escape_help(fam['help'])}")
        lines.append(f"# TYPE {name} {fam['type']}")
        for s in fam["series"]:
            lbl = ",".join(f'{k}="{_escape_label(v)}"'
                           for k, v in s["labels"].items())
            if fam["type"] == "histogram":
                acc = 0
                for le, n in s["buckets"].items():
                    acc += n
                    sep = "," if lbl else ""
                    lines.append(
                        f'{name}_bucket{{{lbl}{sep}le="{le}"}} {acc}')
                brace = f"{{{lbl}}}" if lbl else ""
                lines.append(f"{name}_sum{brace} {_fmt(s['sum'])}")
                lines.append(f"{name}_count{brace} {s['count']}")
            else:
                brace = f"{{{lbl}}}" if lbl else ""
                lines.append(f"{name}{brace} {_fmt(s['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


def merge_snapshots(local: dict, remotes: dict) -> dict:
    """Fold remote registry snapshots into one exposition-ready dict for
    the gateway's federated ``/metrics``.

    ``remotes`` maps a replica name to that worker's full ``snapshot()``.
    Remote series are relabeled with ``replica=<name>`` — unless the series
    already carries a ``replica`` label (the front-door families do), so a
    worker's own attribution is never overwritten.  Local series pass
    through untouched.  A remote family whose type conflicts with an
    already-merged one is skipped (first writer wins) rather than emitting
    an exposition the scraper would reject.
    """
    out = {}
    for name, fam in local.items():
        out[name] = {"type": fam["type"], "help": fam["help"],
                     "series": list(fam["series"])}
    for replica, snap in sorted(remotes.items()):
        for name, fam in snap.items():
            dst = out.get(name)
            if dst is None:
                dst = out[name] = {"type": fam["type"], "help": fam["help"],
                                   "series": []}
            elif dst["type"] != fam["type"]:
                continue
            for s in fam["series"]:
                labels = dict(s["labels"])
                if "replica" not in labels:
                    labels["replica"] = str(replica)
                dst["series"].append({**s, "labels": labels})
    return out


REGISTRY = MetricsRegistry()
