"""RNG — stateful seed surface over JAX functional keys.

Analog of phi::Generator (phi/core/generator.h): Paddle exposes a global stateful
seed; JAX wants explicit threaded keys. The bridge: the generator's key lives inside
a Tensor, so reads/writes go through dispatch and program capture lifts the key to a
program input / mutated output automatically — random ops under to_static get a fresh
key every call instead of a baked constant.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .tensor import Tensor
from . import dispatch


class Generator:
    """Key creation is LAZY: ``PRNGKey`` is a device op, and building it in
    ``__init__`` would initialize the jax backend at ``import paddle_tpu``
    time — every CLI (launcher, bench supervisor) would then dial the
    accelerator tunnel before parsing its arguments."""

    def __init__(self, seed: int = 0):
        self._state = None
        self._seed = seed

    def _ensure_state(self):
        if self._state is None:
            self._state = Tensor(jax.random.PRNGKey(self._seed),
                                 persistable=True)
            self._state.name = "global_rng_state"
        return self._state

    def manual_seed(self, seed: int):
        self._seed = seed
        if self._state is not None:
            # in-place so captured programs that lifted the state Tensor as a
            # program input keep seeing this generator's stream
            self._state._data = jax.random.PRNGKey(seed)
        return self

    def get_state(self) -> Tensor:
        return self._ensure_state()

    def set_state(self, state: Tensor):
        data = state._data if isinstance(state, Tensor) else jnp.asarray(state)
        if self._state is None:
            # build the Tensor straight from the incoming state — going via
            # _ensure_state would run a throwaway PRNGKey device op
            self._state = Tensor(data, persistable=True)
            self._state.name = "global_rng_state"
        else:
            self._state._data = data

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        """Split the state key; returns a fresh subkey (array)."""
        key = dispatch.unwrap(self._ensure_state())
        new_state, sub = jax.random.split(key)
        self._state._data = new_state
        return sub


_default_generator = Generator(0)


def default_generator() -> Generator:
    return _default_generator


def seed(s: int) -> Generator:
    """paddle.seed analog."""
    _default_generator.manual_seed(int(s))
    return _default_generator


def get_rng_state():
    return [_default_generator.get_state()]


def set_rng_state(states):
    _default_generator.set_state(states[0] if isinstance(states, (list, tuple)) else states)


def next_key():
    return _default_generator.next_key()
