"""Shared retry helper — capped exponential backoff with seeded jitter.

The repo previously grew one-off retry loops (the TCPStore client connect
loop slept a flat 0.1s with no jitter; transient engine-step errors simply
killed the serving loop).  This module is the single policy those paths now
share:

    from paddle_tpu.core.retry import RetryPolicy, retry_call

    retry_call(connect, policy=RetryPolicy(max_attempts=8, base_delay=0.05),
               retry_on=(OSError,), op="store.connect")

Backoff is the standard ``min(max_delay, base * multiplier**i)`` curve with
*equal jitter* (half fixed, half uniform-random) so simultaneous retriers
decorrelate instead of stampeding; the jitter stream is seeded per call, so a
test passing ``seed=`` replays byte-identical sleep schedules.  Attempt
counts land in the observability registry (``retry_attempts`` histogram +
``retry_exhausted_total``, labelled by ``op``) whenever telemetry is on.
"""
from __future__ import annotations

import random
import time

__all__ = ["RetryPolicy", "RetryError", "retry_call"]


class RetryError(RuntimeError):
    """All attempts failed (or the deadline lapsed); ``__cause__`` is the
    last underlying error and ``attempts`` how many were made."""

    def __init__(self, op, attempts, last):
        super().__init__(
            f"{op or 'operation'} failed after {attempts} attempt(s): "
            f"{type(last).__name__}: {last}")
        self.attempts = attempts


class RetryPolicy:
    """Backoff shape: ``max_attempts`` total tries, delays growing from
    ``base_delay`` by ``multiplier`` capped at ``max_delay``, each delay
    jittered to ``[delay/2, delay]`` (equal jitter).  ``deadline`` bounds the
    whole retried operation in wall seconds — no sleep is started that the
    deadline could not cover.  ``seed`` fixes the jitter stream (tests)."""

    def __init__(self, max_attempts=5, base_delay=0.05, max_delay=2.0,
                 multiplier=2.0, jitter=True, deadline=None, seed=None):
        if int(max_attempts) < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = bool(jitter)
        self.deadline = deadline
        self.seed = seed

    def delays(self):
        """The sleep schedule between attempts (``max_attempts - 1`` values)."""
        rng = random.Random(self.seed)
        for i in range(self.max_attempts - 1):
            d = min(self.max_delay, self.base_delay * self.multiplier ** i)
            if self.jitter:
                d = d / 2 + rng.uniform(0, d / 2)
            yield d


def retry_call(fn, *args, policy=None, retry_on=(Exception,), op="",
               on_retry=None, sleep=time.sleep, clock=time.monotonic,
               **kwargs):
    """Call ``fn(*args, **kwargs)``, retrying on ``retry_on`` errors per
    ``policy`` (default :class:`RetryPolicy`).  ``on_retry(attempt, err,
    delay)`` observes each failure before its backoff sleep; ``sleep`` and
    ``clock`` are injectable for deterministic tests.  Raises
    :class:`RetryError` (from the last error) when attempts or the deadline
    run out; non-matching errors propagate immediately."""
    policy = policy or RetryPolicy()
    start = clock()
    delays = policy.delays()
    attempt = 0
    while True:
        attempt += 1
        try:
            result = fn(*args, **kwargs)
        except retry_on as e:
            delay = next(delays, None)
            expired = (policy.deadline is not None and delay is not None
                       and clock() - start + delay > policy.deadline)
            if delay is None or expired:
                _record(op, attempt, exhausted=True)
                raise RetryError(op, attempt, e) from e
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
        else:
            _record(op, attempt, exhausted=False)
            return result


def _record(op, attempts, exhausted):
    """Mirror the outcome into the registry; free while telemetry is off.
    Lazy import: core must stay importable without the observability pkg."""
    from .. import observability as _obs
    if not _obs.enabled():
        return
    _obs.RETRY_ATTEMPTS.labels(op=op or "unknown").observe(attempts)
    if exhausted:
        _obs.RETRY_EXHAUSTED.labels(op=op or "unknown").inc()
