"""Device / place management.

Paddle's Place hierarchy (phi/common/place.h) collapses here to jax.Device: TPU is
the first-class target, CPU is the test backend. `set_device`/`get_device` keep the
Paddle string surface ("tpu", "tpu:0", "cpu").
"""
from __future__ import annotations

import functools

import jax


class Place:
    """Lightweight place wrapper over a jax.Device (phi/common/place.h analog)."""

    __slots__ = ("device",)

    def __init__(self, device: jax.Device):
        self.device = device

    @property
    def platform(self) -> str:
        return self.device.platform

    def is_tpu_place(self) -> bool:
        return self.device.platform in ("tpu", "axon")

    def is_cpu_place(self) -> bool:
        return self.device.platform == "cpu"

    def is_gpu_place(self) -> bool:
        return self.device.platform in ("gpu", "cuda")

    def __eq__(self, other):
        if isinstance(other, Place):
            return self.device == other.device
        return NotImplemented

    def __hash__(self):
        return hash(self.device)

    def __repr__(self):
        return f"Place({self.device.platform}:{self.device.id})"


_current_device = None


def _parse(device):
    if device is None:
        return None
    if isinstance(device, jax.Device):
        return device
    if isinstance(device, Place):
        return device.device
    if isinstance(device, str):
        name, _, idx = device.partition(":")
        idx = int(idx) if idx else 0
        name = {"tpu": None, "gpu": None, "xpu": None}.get(name, name) or _accel_platform()
        devs = [d for d in jax.devices() if d.platform == name]
        if not devs:
            devs = jax.devices(name)
        return devs[idx]
    raise ValueError(f"cannot parse device spec {device!r}")


@functools.lru_cache(None)
def _accel_platform() -> str:
    """Best accelerator platform available (tpu under axon tunnel shows as its own platform)."""
    plats = {d.platform for d in jax.devices()}
    for p in ("tpu", "axon", "gpu", "cuda"):
        if p in plats:
            return p
    return "cpu"


def set_device(device) -> Place:
    """paddle.set_device analog (python/paddle/device/__init__.py)."""
    global _current_device
    _current_device = _parse(device)
    jax.config.update("jax_default_device", _current_device)
    return Place(_current_device)


def get_device():
    d = _current_device or jax.devices()[0]
    return f"{d.platform}:{d.id}"


def current_device() -> jax.Device:
    return _current_device or jax.devices()[0]


def current_place() -> Place:
    return Place(current_device())


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


def device_count() -> int:
    return len(jax.devices())
