"""Structured errors — PADDLE_ENFORCE analog (common/enforce.h, common/errors.cc).

Typed error classes matching the reference's common::errors taxonomy, plus enforce_*
helpers that raise them with op-context attribution.
"""
from __future__ import annotations


class EnforceError(RuntimeError):
    code = "FATAL"

    def __init__(self, msg, op=None):
        if op:
            msg = f"(op: {op}) {msg}"
        super().__init__(f"[{self.code}] {msg}")


class InvalidArgumentError(EnforceError, ValueError):
    code = "InvalidArgument"


class NotFoundError(EnforceError, KeyError):
    code = "NotFound"


class OutOfRangeError(EnforceError, IndexError):
    code = "OutOfRange"


class AlreadyExistsError(EnforceError):
    code = "AlreadyExists"


class PermissionDeniedError(EnforceError):
    code = "PermissionDenied"


class UnimplementedError(EnforceError, NotImplementedError):
    code = "Unimplemented"


class UnavailableError(EnforceError):
    code = "Unavailable"


class PreconditionNotMetError(EnforceError):
    code = "PreconditionNotMet"


class ExecutionTimeoutError(EnforceError):
    code = "ExecutionTimeout"


def enforce(cond, msg="enforce failed", op=None, err=PreconditionNotMetError):
    if not cond:
        raise err(msg, op=op)


def enforce_eq(a, b, msg="", op=None):
    if a != b:
        raise InvalidArgumentError(f"expected {a!r} == {b!r}. {msg}", op=op)


def enforce_gt(a, b, msg="", op=None):
    if not a > b:
        raise InvalidArgumentError(f"expected {a!r} > {b!r}. {msg}", op=op)


def enforce_ge(a, b, msg="", op=None):
    if not a >= b:
        raise InvalidArgumentError(f"expected {a!r} >= {b!r}. {msg}", op=op)


def enforce_not_none(v, name="value", op=None):
    if v is None:
        raise InvalidArgumentError(f"{name} must not be None", op=op)
    return v
