"""Dtype system.

Paddle exposes a closed dtype enum (paddle/phi/common/data_type.h); here dtypes ARE
numpy/jax dtypes so everything interoperates with jnp for free. We keep the Paddle
string names ("float32", "bfloat16", ...) and the `paddle.float32` style aliases.
bfloat16 is the default compute dtype on TPU, float32 the default parameter dtype.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = ml_dtypes.float8_e4m3fn
float8_e5m2 = ml_dtypes.float8_e5m2

_ALIASES = {
    "bool": bool_, "uint8": uint8, "int8": int8, "int16": int16,
    "int32": int32, "int64": int64, "float16": float16, "bfloat16": bfloat16,
    "float32": float32, "float64": float64, "complex64": complex64,
    "complex128": complex128, "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    # paddle legacy VarType names
    "FP32": float32, "FP64": float64, "FP16": float16, "BF16": bfloat16,
    "INT32": int32, "INT64": int64, "BOOL": bool_,
}

_FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
_INTEGER = {uint8, int8, int16, int32, int64}


def convert_dtype(dtype):
    """Normalize any dtype spec (str, np/jnp dtype, None) to a numpy dtype object.

    When JAX x64 is disabled (the TPU default), int64/float64 requests narrow to
    the native 32-bit types silently — Paddle's int64 surface, TPU-native storage.
    """
    if dtype is None:
        return None
    if isinstance(dtype, str):
        d = np.dtype(_ALIASES[dtype]) if dtype in _ALIASES else np.dtype(dtype)
    else:
        d = np.dtype(dtype)
    if not _x64_enabled():
        if d == np.dtype(np.int64):
            return np.dtype(np.int32)
        if d == np.dtype(np.float64):
            return np.dtype(np.float32)
        if d == np.dtype(np.uint64):
            return np.dtype(np.uint32)
        if d == np.dtype(np.complex128):
            return np.dtype(np.complex64)
    return d


def _x64_enabled() -> bool:
    import jax
    return bool(jax.config.read("jax_enable_x64"))


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def is_floating_point(dtype) -> bool:
    d = np.dtype(dtype)
    return any(d == np.dtype(f) for f in _FLOATING)


def is_integer(dtype) -> bool:
    d = np.dtype(dtype)
    return any(d == np.dtype(i) for i in _INTEGER) or d == np.dtype(np.bool_)


def is_complex(dtype) -> bool:
    d = np.dtype(dtype)
    return d in (np.dtype(np.complex64), np.dtype(np.complex128))


# Default dtype management (paddle.set_default_dtype analog;
# reference: python/paddle/base/framework.py get_default_dtype)
_default_dtype = np.dtype(np.float32)


def set_default_dtype(d):
    global _default_dtype
    d = convert_dtype(d)
    if not is_floating_point(d):
        raise TypeError(f"set_default_dtype only supports floating dtypes, got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype
