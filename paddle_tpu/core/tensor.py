"""Eager Tensor.

Analog of the reference's eager Tensor (paddle/fluid/pybind/eager.cc:1477 binding over
phi::DenseTensor, autograd meta fluid/eager/autograd_meta.h:61) — redesigned for a
functional runtime: `_data` holds an immutable jax.Array (or a JAX tracer during
program capture), so the SAME eager code runs op-by-op on PJRT *and* under jit trace.
Because jax arrays are immutable, saved-tensor/inplace-version tracking from the
reference (fluid/eager/tensor_wrapper.h) is unnecessary: vjp residuals capture values,
not buffers.

Autograd state mirrors AutogradMeta: `stop_gradient` (default True, like Paddle),
`grad`, and a producer `_grad_node` + `_out_slot` linking into the tape
(see paddle_tpu/autograd/node.py).
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtypes
from .device import Place, current_device


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class Tensor:
    __slots__ = (
        "_buf", "stop_gradient", "_grad_buf", "_grad_node", "_out_slot",
        "name", "persistable", "_retain_grad", "_hooks", "_replay_node",
        "__weakref__",
    )

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None,
                 persistable: bool = False):
        self._buf = data
        self.stop_gradient = stop_gradient
        self._grad_buf: Optional[Tensor] = None
        self._grad_node = None
        self._out_slot = 0
        self._replay_node = None   # (node, slot) set under static recording
        self.name = name
        self.persistable = persistable
        self._retain_grad = False
        self._hooks: Optional[list] = None
        from .dispatch import _state
        tc = _state.trace_ctx
        if tc is not None:
            tc.on_create(self)

    # -- data access: reads/writes route through properties so program capture
    # (paddle_tpu.jit) can lift state (params, opt moments, RNG keys) to program
    # inputs and collect mutations as outputs without touching the real buffers.
    @property
    def _data(self):
        from .dispatch import _state
        tc = _state.trace_ctx
        if tc is not None:
            return tc.on_read(self)
        return self._buf

    @_data.setter
    def _data(self, value):
        from .dispatch import _state
        tc = _state.trace_ctx
        if tc is not None:
            tc.on_write(self, value)
            return
        self._buf = value

    @property
    def grad(self):
        from .dispatch import _state
        tc = _state.trace_ctx
        if tc is not None:
            return tc.on_grad_read(self)
        return self._grad_buf

    @grad.setter
    def grad(self, value):
        from .dispatch import _state
        tc = _state.trace_ctx
        if tc is not None:
            tc.on_grad_write(self, value)
            return
        self._grad_buf = value

    # ---- metadata ------------------------------------------------------------
    @property
    def shape(self) -> list:
        return list(self._buf.shape)

    @property
    def ndim(self) -> int:
        return self._buf.ndim

    @property
    def dtype(self):
        return np.dtype(self._buf.dtype)

    @property
    def size(self) -> int:
        return int(np.prod(self._buf.shape)) if self._buf.shape else 1

    @property
    def place(self) -> Place:
        if _is_tracer(self._buf):
            return Place(current_device())
        devs = getattr(self._buf, "devices", None)
        if devs is not None:
            return Place(next(iter(self._buf.devices())))
        return Place(current_device())

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def T(self) -> "Tensor":
        return self.transpose(list(range(self.ndim))[::-1])

    def numel(self) -> int:
        return self.size

    def element_size(self) -> int:
        return self.dtype.itemsize

    def dim(self) -> int:
        return self.ndim

    def is_dist(self) -> bool:
        if _is_tracer(self._buf):
            return False
        sharding = getattr(self._buf, "sharding", None)
        return sharding is not None and getattr(sharding, "num_devices", 1) > 1

    # ---- host interop --------------------------------------------------------
    def numpy(self) -> np.ndarray:
        """Host read. Under program capture this is a stitched BREAK event
        (jit/to_static.py): the compiled program emits the traced value as an
        extra output, and the per-call echo pass hands the caller the true
        array — the signature stays compiled."""
        from .dispatch import _state
        tc = _state.trace_ctx
        if tc is not None and hasattr(tc, "on_materialize"):
            return tc.on_materialize(self)
        if _is_tracer(self._buf):
            raise RuntimeError(
                "Tensor.numpy() is not available while capturing a static program "
                "(data-dependent host access); this triggers a graph break.")
        return np.asarray(self._buf)

    def item(self, *args):
        return self.numpy().item(*args)

    def tolist(self):
        return self.numpy().tolist()

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._buf.shape[0]

    def _convert_scalar(self, kind, caster):
        """Host scalar conversion. Under program capture this is a GUARD
        point (the SOT guard analog, jit/to_static.py): the spy pass records
        the concrete value; replay emits the traced value as a program output
        and the runtime re-specializes when a step's actual value diverges."""
        from .dispatch import _state
        tc = _state.trace_ctx
        if tc is not None and hasattr(tc, "on_scalar"):
            return tc.on_scalar(self, kind, caster)
        return caster(self._data)

    def __bool__(self) -> bool:
        return self._convert_scalar("bool", lambda a: bool(a))

    def __int__(self) -> int:
        return self._convert_scalar("int", lambda a: int(a))

    def __float__(self) -> float:
        # a float guard would re-specialize on every distinct value, so under
        # capture this is a stitched BREAK (traced value rides out as a
        # program output; the echo pass returns the true per-call float)
        return self._convert_scalar("float", lambda a: float(a))

    def __index__(self) -> int:
        return self._convert_scalar("int", lambda a: int(a))

    def __format__(self, spec):
        if self.ndim == 0:
            from .dispatch import _state
            tc = _state.trace_ctx
            if tc is not None and hasattr(tc, "on_materialize"):
                return format(np.asarray(tc.on_materialize(self)).item(), spec)
            if not _is_tracer(self._buf):
                return format(self.item(), spec)
        return str(self)

    # ---- autograd surface ----------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        from ..autograd.backward import backward as _backward
        _backward([self], [grad_tensor], retain_graph=retain_graph)

    def register_hook(self, hook):
        """Register a gradient hook; returns a removable handle (eager hook analog
        of fluid/eager/hooks.h)."""
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)
        hooks = self._hooks
        class _Handle:
            def remove(self_inner):
                if hook in hooks:
                    hooks.remove(hook)
        return _Handle()

    def retain_grads(self):
        self._retain_grad = True

    def clear_grad(self, set_to_zero: bool = False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._buf))
        else:
            self.grad = None

    def clear_gradient(self, set_to_zero: bool = False):
        self.clear_grad(set_to_zero)

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def detach_(self) -> "Tensor":
        self._grad_node = None
        self.stop_gradient = True
        return self

    # ---- conversion / movement ----------------------------------------------
    def to(self, *args, **kwargs) -> "Tensor":
        device = kwargs.pop("device", None)
        dtype = kwargs.pop("dtype", None)
        blocking = kwargs.pop("blocking", None)  # noqa: F841 — async by default on TPU
        for a in args:
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu") or ":" in str(a):
                device = a
            else:
                dtype = a
        out = self
        if dtype is not None:
            out = out.astype(dtype)
        if device is not None:
            from .device import _parse
            arr = jax.device_put(out._buf, _parse(device))
            t = Tensor(arr, stop_gradient=out.stop_gradient, name=out.name)
            t._grad_node, t._out_slot = out._grad_node, out._out_slot
            out = t
        return out

    def cpu(self) -> "Tensor":
        return self.to(device="cpu")

    def cuda(self, *a, **k) -> "Tensor":  # paddle compat name; routes to accelerator
        from .device import _accel_platform
        return self.to(device=_accel_platform())

    def pin_memory(self) -> "Tensor":
        return self

    def contiguous(self) -> "Tensor":
        return self

    def is_contiguous(self) -> bool:
        return True

    # astype installed by ops package (differentiable cast); cast = alias.

    # ---- misc ----------------------------------------------------------------
    def get_tensor(self):
        return self

    def value(self):
        return self

    def block_until_ready(self) -> "Tensor":
        if not _is_tracer(self._buf):
            jax.block_until_ready(self._buf)
        return self

    def _copy_from(self, other: "Tensor"):
        self._data = other._buf if isinstance(other, Tensor) else jnp.asarray(other)

    def copy_(self, other, blocking: bool = True) -> "Tensor":
        self._copy_from(other)
        return self

    def __repr__(self):
        if _is_tracer(self._buf):
            return (f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
                    f"traced=True, stop_gradient={self.stop_gradient})")
        data = np.asarray(self._buf)
        return (f"Tensor(shape={self.shape}, dtype={dtypes.dtype_name(self.dtype)}, "
                f"place={self.place}, stop_gradient={self.stop_gradient},\n"
                f"       {np.array2string(data, prefix='       ')})")

    __str__ = __repr__

    # Elementwise __eq__ is installed by ops.logic; keep identity hashing so
    # Tensors can key dicts (optimizer state, reducers) like Paddle's Tensor.
    __hash__ = object.__hash__

    # numpy interop
    def __array__(self, dtype=None):
        arr = self.numpy()
        return arr.astype(dtype) if dtype is not None else arr


class Parameter(Tensor):
    """Trainable tensor (python/paddle/base/framework.py Parameter analog)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name, persistable=True)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()
