// Native batch-gather engine for paddle_tpu.io.DataLoader.
//
// Reference analog: the C++ data plane of paddle/fluid/framework/data_feed.cc
// and the DataLoader worker pool — the host-side hot loop of training input
// pipelines. Here the engine owns a pool of pthreads that gather rows of a
// caller-held contiguous array into double-buffered batch buffers ahead of
// consumption, delivering batches strictly in submission order.
//
// Contract (all functions thread-safe w.r.t. one engine):
//   pt_dl_create(data, n_rows, row_bytes, n_threads, depth) -> handle
//       `data` must stay valid until pt_dl_destroy (Python holds the array).
//       depth bounds in-flight + finished-but-unconsumed batches (memory cap).
//   pt_dl_submit(h, idx, n)   enqueue one batch (row indices); returns 0, or
//                             -1 after close / bad index.
//   pt_dl_acquire(h, &ptr)    block until the NEXT batch (submission order) is
//                             ready; returns its row count, ptr to its bytes.
//                             Returns -1 once closed and fully drained.
//                             The pointer stays valid until the following
//                             acquire (one-slot consumer ownership).
//   pt_dl_release(h)          optional early recycle of the acquired buffer.
//   pt_dl_close(h)            no more submissions; workers drain then exit.
//   pt_dl_destroy(h)          join threads, free everything.
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Job {
  int64_t seq;
  std::vector<int64_t> idx;
};

struct Engine {
  const uint8_t* data = nullptr;
  int64_t n_rows = 0;
  int64_t row_bytes = 0;
  int depth = 2;

  std::mutex m;
  std::condition_variable cv_worker;    // jobs available / room to work
  std::condition_variable cv_consumer;  // finished batch available
  std::deque<Job> jobs;
  std::map<int64_t, std::pair<std::vector<uint8_t>, int64_t>> done;  // seq -> (buf, rows)
  int64_t next_submit = 0;
  int64_t next_deliver = 0;
  int64_t in_flight = 0;
  bool closed = false;
  bool dead = false;
  std::vector<uint8_t> current;  // consumer-owned slot
  std::vector<std::thread> threads;
};

void worker_main(Engine* e) {
  for (;;) {
    Job job;
    {
      std::unique_lock<std::mutex> lk(e->m);
      e->cv_worker.wait(lk, [e] {
        // bound finished-but-unconsumed memory: only start a job when its
        // result will be within `depth` of the consumer's cursor
        return e->dead ||
               (!e->jobs.empty() &&
                e->jobs.front().seq < e->next_deliver + e->depth);
      });
      if (e->dead) return;
      if (e->jobs.empty() ||
          e->jobs.front().seq >= e->next_deliver + e->depth)
        continue;
      job = std::move(e->jobs.front());
      e->jobs.pop_front();
      e->in_flight++;
    }
    std::vector<uint8_t> buf(job.idx.size() * e->row_bytes);
    for (size_t r = 0; r < job.idx.size(); ++r) {
      std::memcpy(buf.data() + r * e->row_bytes,
                  e->data + job.idx[r] * e->row_bytes,
                  static_cast<size_t>(e->row_bytes));
    }
    {
      std::unique_lock<std::mutex> lk(e->m);
      e->done.emplace(job.seq,
                      std::make_pair(std::move(buf),
                                     static_cast<int64_t>(job.idx.size())));
      e->in_flight--;
      e->cv_consumer.notify_all();
    }
  }
}

}  // namespace

extern "C" {

void* pt_dl_create(const void* data, int64_t n_rows, int64_t row_bytes,
                   int n_threads, int depth) {
  if (data == nullptr || n_rows < 0 || row_bytes <= 0) return nullptr;
  Engine* e = new Engine();
  e->data = static_cast<const uint8_t*>(data);
  e->n_rows = n_rows;
  e->row_bytes = row_bytes;
  e->depth = depth < 1 ? 1 : depth;
  int t = n_threads < 1 ? 1 : (n_threads > 64 ? 64 : n_threads);
  e->threads.reserve(t);
  for (int i = 0; i < t; ++i) e->threads.emplace_back(worker_main, e);
  return e;
}

int pt_dl_submit(void* h, const int64_t* idx, int64_t n) {
  Engine* e = static_cast<Engine*>(h);
  if (e == nullptr || n < 0) return -1;
  Job job;
  job.idx.assign(idx, idx + n);
  for (int64_t i = 0; i < n; ++i)
    if (idx[i] < 0 || idx[i] >= e->n_rows) return -1;
  std::unique_lock<std::mutex> lk(e->m);
  if (e->closed || e->dead) return -1;
  job.seq = e->next_submit++;
  e->jobs.push_back(std::move(job));
  e->cv_worker.notify_all();
  return 0;
}

int64_t pt_dl_acquire(void* h, const void** out_ptr) {
  Engine* e = static_cast<Engine*>(h);
  *out_ptr = nullptr;
  std::unique_lock<std::mutex> lk(e->m);
  // recycle the previous slot and wake workers whose depth window moved
  e->current.clear();
  e->current.shrink_to_fit();
  for (;;) {
    auto it = e->done.find(e->next_deliver);
    if (it != e->done.end()) {
      e->current = std::move(it->second.first);
      int64_t rows = it->second.second;
      e->done.erase(it);
      e->next_deliver++;
      e->cv_worker.notify_all();
      *out_ptr = e->current.data();
      return rows;
    }
    bool drained = e->closed && e->jobs.empty() && e->in_flight == 0 &&
                   e->done.empty();
    if (drained || e->dead) return -1;
    e->cv_consumer.wait(lk);
  }
}

void pt_dl_release(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock<std::mutex> lk(e->m);
  e->current.clear();
  e->current.shrink_to_fit();
}

void pt_dl_close(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::unique_lock<std::mutex> lk(e->m);
  e->closed = true;
  e->cv_worker.notify_all();
  e->cv_consumer.notify_all();
}

void pt_dl_destroy(void* h) {
  Engine* e = static_cast<Engine*>(h);
  {
    std::unique_lock<std::mutex> lk(e->m);
    e->dead = true;
    e->cv_worker.notify_all();
    e->cv_consumer.notify_all();
  }
  for (auto& t : e->threads) t.join();
  delete e;
}

}  // extern "C"
