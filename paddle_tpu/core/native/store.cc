// Native TCPStore server (reference: paddle/phi/core/distributed/store/
// tcp_store.h:121 MasterDaemon + tcp_utils.cc — the reference's rendezvous
// KV store is exactly this C++ daemon; the Python TCPStore class is a thin
// client over it).
//
// Wire protocol (shared with the Python client/fallback server):
//   request : u8 cmd | u32 klen | key | u32 vlen | val | f64 timeout   (BE)
//   response: u8 status (0 ok, 1 timeout, 2 bad, 3 deleted-miss) | u32 vlen | val
//   cmds: 1 SET  2 GET(blocking until key or timeout; a DELETE processed
//           mid-wait answers status 3 instead of stalling)  3 ADD(val=i64 BE)
//         4 DELETE  5 WAIT(key = '\n'-joined key list)
//         6 CAS(val = u32 elen | expected | desired; elen 0 = expect-absent;
//           reply val = u8 swapped | current bytes)
//
// Threading mirrors tcp_store.cc: accept loop + thread per connection over
// one mutex/condvar-protected map. Exposed flat C API for ctypes.
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Store {
  std::map<std::string, std::string> kv;
  std::map<std::string, uint64_t> dels;  // key -> deletion generation
  std::mutex mu;
  std::condition_variable cv;
  int listen_fd = -1;
  std::thread accept_thread;
  bool stopping = false;
};

Store* g_store = nullptr;
std::mutex g_mu;

bool read_exact(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_exact(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool read_u32(int fd, uint32_t* v) {
  uint32_t be;
  if (!read_exact(fd, &be, 4)) return false;
  *v = ntohl(be);
  return true;
}

bool read_blob(int fd, std::string* out) {
  uint32_t n;
  if (!read_u32(fd, &n)) return false;
  out->resize(n);
  return n == 0 || read_exact(fd, &(*out)[0], n);
}

bool send_reply(int fd, uint8_t status, const std::string& val) {
  std::string buf;
  buf.push_back(static_cast<char>(status));
  uint32_t be = htonl(static_cast<uint32_t>(val.size()));
  buf.append(reinterpret_cast<char*>(&be), 4);
  buf.append(val);
  return write_exact(fd, buf.data(), buf.size());
}

void serve(Store* st, int fd) {
  for (;;) {
    uint8_t cmd;
    std::string key, val;
    uint64_t tbits;
    if (!read_exact(fd, &cmd, 1) || !read_blob(fd, &key) ||
        !read_blob(fd, &val) || !read_exact(fd, &tbits, 8))
      break;
    uint64_t host_bits = be64toh(tbits);
    double timeout;
    std::memcpy(&timeout, &host_bits, 8);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<std::chrono::steady_clock::duration>(
                        std::chrono::duration<double>(timeout));
    bool ok = true;
    switch (cmd) {
      case 1: {  // SET
        {
          std::lock_guard<std::mutex> lk(st->mu);
          st->kv[key] = val;
        }
        st->cv.notify_all();
        ok = send_reply(fd, 0, "");
        break;
      }
      case 2: {  // GET (blocking; DELETE mid-wait -> typed miss, status 3)
        std::unique_lock<std::mutex> lk(st->mu);
        uint64_t gen0 = st->dels.count(key) ? st->dels[key] : 0;
        st->cv.wait_until(lk, deadline, [&] {
          return st->stopping || st->kv.count(key) != 0 ||
                 (st->dels.count(key) ? st->dels[key] : 0) != gen0;
        });
        if (st->kv.count(key)) {
          ok = send_reply(fd, 0, st->kv[key]);
        } else {
          bool deleted = (st->dels.count(key) ? st->dels[key] : 0) != gen0;
          lk.unlock();
          ok = send_reply(fd, deleted ? 3 : 1, "");
        }
        break;
      }
      case 3: {  // ADD
        int64_t delta = 0;
        if (val.size() == 8) {
          uint64_t be;
          std::memcpy(&be, val.data(), 8);
          delta = static_cast<int64_t>(be64toh(be));
        }
        int64_t cur;
        {
          std::lock_guard<std::mutex> lk(st->mu);
          int64_t prev = 0;
          auto it = st->kv.find(key);
          if (it != st->kv.end()) prev = std::strtoll(it->second.c_str(), nullptr, 10);
          cur = prev + delta;
          st->kv[key] = std::to_string(cur);
        }
        st->cv.notify_all();
        uint64_t be = htobe64(static_cast<uint64_t>(cur));
        ok = send_reply(fd, 0, std::string(reinterpret_cast<char*>(&be), 8));
        break;
      }
      case 4: {  // DELETE
        bool existed;
        {
          std::lock_guard<std::mutex> lk(st->mu);
          existed = st->kv.erase(key) != 0;
          st->dels[key]++;
        }
        st->cv.notify_all();
        ok = send_reply(fd, 0, existed ? "1" : "0");
        break;
      }
      case 6: {  // CAS: expected raw bytes (elen 0 = expect-absent) -> desired
        if (val.size() < 4) {
          ok = send_reply(fd, 2, "");
          break;
        }
        uint32_t elen_be;
        std::memcpy(&elen_be, val.data(), 4);
        uint32_t elen = ntohl(elen_be);
        if (val.size() < 4 + static_cast<size_t>(elen)) {
          ok = send_reply(fd, 2, "");
          break;
        }
        std::string expected = val.substr(4, elen);
        std::string desired = val.substr(4 + elen);
        std::string reply;
        {
          std::lock_guard<std::mutex> lk(st->mu);
          auto it = st->kv.find(key);
          bool swapped = (elen == 0) ? it == st->kv.end()
                                     : (it != st->kv.end() && it->second == expected);
          if (swapped) st->kv[key] = desired;
          reply.push_back(swapped ? '\x01' : '\x00');
          auto cur = st->kv.find(key);
          if (cur != st->kv.end()) reply.append(cur->second);
        }
        st->cv.notify_all();
        ok = send_reply(fd, 0, reply);
        break;
      }
      case 5: {  // WAIT on '\n'-joined keys
        std::vector<std::string> keys;
        size_t pos = 0;
        while (pos <= key.size() && !key.empty()) {
          size_t nl = key.find('\n', pos);
          if (nl == std::string::npos) {
            keys.push_back(key.substr(pos));
            break;
          }
          keys.push_back(key.substr(pos, nl - pos));
          pos = nl + 1;
        }
        bool all = true;
        {
          std::unique_lock<std::mutex> lk(st->mu);
          for (const auto& k : keys) {
            bool have = st->cv.wait_until(lk, deadline, [&] {
              return st->stopping || st->kv.count(k) != 0;
            });
            if (!have || !st->kv.count(k)) {
              all = false;
              break;
            }
          }
        }
        ok = send_reply(fd, all ? 0 : 1, "");
        break;
      }
      default:
        ok = send_reply(fd, 2, "");
    }
    if (!ok) break;
  }
  ::close(fd);
}

void accept_loop(Store* st) {
  for (;;) {
    int fd = ::accept(st->listen_fd, nullptr, nullptr);
    if (fd < 0) return;  // listen socket closed: shut down
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(serve, st, fd).detach();
  }
}

}  // namespace

extern "C" {

// Start the daemon on host:port (port 0 = ephemeral). Returns the bound
// port, or -1 on error. One daemon per process (the master rank's).
int pt_store_start(const char* host, int port) {
  std::lock_guard<std::mutex> g(g_mu);
  if (g_store != nullptr) return -1;
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = host && *host ? ::inet_addr(host) : INADDR_ANY;
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return -1;
  }
  socklen_t len = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &len);
  auto* st = new Store();
  st->listen_fd = fd;
  st->accept_thread = std::thread(accept_loop, st);
  st->accept_thread.detach();
  g_store = st;
  return ntohs(addr.sin_port);
}

void pt_store_stop() {
  std::lock_guard<std::mutex> g(g_mu);
  if (g_store == nullptr) return;
  {
    std::lock_guard<std::mutex> lk(g_store->mu);
    g_store->stopping = true;
  }
  g_store->cv.notify_all();
  ::shutdown(g_store->listen_fd, SHUT_RDWR);
  ::close(g_store->listen_fd);
  g_store = nullptr;  // leak the Store: detached threads may still hold it
}

}  // extern "C"
