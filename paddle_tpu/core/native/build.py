"""Lazy native-code builder: compile a .cc beside this package into a cached
.so and load it via ctypes (reference equivalent: the paddle build links
phi's C++ runtime; here native pieces compile on first use and every caller
has a pure-Python fallback, so a missing toolchain never breaks the wheel).

Cache: $PADDLE_TPU_NATIVE_CACHE or ~/.cache/paddle_tpu/native/<name>-<hash>.so
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_lock = threading.Lock()
_loaded: dict = {}


def _cache_dir():
    return os.environ.get(
        "PADDLE_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "native"))


def load(name: str, source_file: str, extra_flags=()):
    """Compile+load <dir of build.py>/<source_file> as a shared lib.
    Returns ctypes.CDLL, or None when no toolchain / compile error
    (callers fall back to their Python implementation)."""
    with _lock:
        if name in _loaded:
            return _loaded[name]
        src = os.path.join(os.path.dirname(__file__), source_file)
        try:
            with open(src, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
        except OSError:
            _loaded[name] = None
            return None
        out = os.path.join(_cache_dir(), f"{name}-{digest}.so")
        if not os.path.exists(out):
            os.makedirs(_cache_dir(), exist_ok=True)
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-pthread", src, "-o", out + ".tmp", *extra_flags]
            try:
                r = subprocess.run(cmd, capture_output=True, timeout=120)
                if r.returncode != 0:
                    _loaded[name] = None
                    return None
                os.replace(out + ".tmp", out)
            except (OSError, subprocess.TimeoutExpired):
                _loaded[name] = None
                return None
        try:
            _loaded[name] = ctypes.CDLL(out)
        except OSError:
            _loaded[name] = None
        return _loaded[name]
