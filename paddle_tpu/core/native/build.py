"""Lazy native-code builder: compile a .cc beside this package into a cached
.so and load it via ctypes (reference equivalent: the paddle build links
phi's C++ runtime; here native pieces compile on first use and every caller
has a pure-Python fallback, so a missing toolchain never breaks the wheel).

Cache: $PADDLE_TPU_NATIVE_CACHE or ~/.cache/paddle_tpu/native/<name>-<hash>.so
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_lock = threading.Lock()
_loaded: dict = {}     # (name, source digest) -> CDLL | None
_errors: dict = {}     # name -> last failure diagnostic


def last_error(name: str):
    """Diagnostic from the most recent failed load() of `name`."""
    return _errors.get(name)


def _cache_dir():
    return os.environ.get(
        "PADDLE_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu",
                     "native"))


def load(name: str, source_file: str, extra_flags=()):
    """Compile+load <dir of build.py>/<source_file> as a shared lib.
    Returns ctypes.CDLL, or None when no toolchain / compile error
    (callers fall back to their Python implementation)."""
    with _lock:
        src = source_file if os.path.isabs(source_file) else \
            os.path.join(os.path.dirname(__file__), source_file)
        try:
            with open(src, "rb") as f:
                digest = hashlib.sha256(f.read()).hexdigest()[:16]
        except OSError as e:
            _errors[name] = f"cannot read {src}: {e}"
            return None
        # memo keyed by content digest: fixing the source and re-calling
        # load() in the same process retries instead of replaying a failure
        memo = (name, digest)
        if memo in _loaded:
            return _loaded[memo]
        out = os.path.join(_cache_dir(), f"{name}-{digest}.so")
        if not os.path.exists(out):
            os.makedirs(_cache_dir(), exist_ok=True)
            cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
                   "-pthread", src, "-o", out + ".tmp", *extra_flags]
            try:
                r = subprocess.run(cmd, capture_output=True, timeout=120)
                if r.returncode != 0:
                    _errors[name] = r.stderr.decode(errors="replace")[-4000:]
                    _loaded[memo] = None
                    return None
            except (OSError, subprocess.TimeoutExpired) as e:
                _errors[name] = f"g++ unavailable or timed out: {e}"
                _loaded[memo] = None
                return None
            os.replace(out + ".tmp", out)
        try:
            _loaded[memo] = ctypes.CDLL(out)
        except OSError as e:
            _errors[name] = f"dlopen failed: {e}"
            _loaded[memo] = None
        return _loaded[memo]
