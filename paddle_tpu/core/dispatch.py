"""Op dispatch — the single chokepoint every operator goes through.

Reference analog: the generated `xxx_ad_func` layer (fluid/eager/auto_code_generator/
generator/eager_gen.py) + phi kernel dispatch (phi/core/kernel_factory.h:326). Here an
op is a pure jax function over arrays; dispatch:

  1. unwraps Tensor args (via the active trace context if capturing, so concrete
     values read inside a captured region are lifted to program inputs),
  2. applies AMP autocast if active,
  3. runs the fn — or `jax.vjp(fn, ...)` when any input requires grad — and
  4. wraps outputs in Tensors, recording a GradNode on the tape.

Everything works identically on concrete arrays and on tracers, which is what makes
program capture (paddle_tpu.jit.to_static) a pure re-execution of eager code.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable

import numpy as np
import jax
import jax.numpy as jnp

from .tensor import Tensor
from . import flags

# Process-wide metrics recorder (observability.enable()). Thread-locals are
# seeded from it on first access, so apply_op keeps exactly ONE
# instrumentation branch for both the profiler and the metrics registry.
_metrics_recorder = None


class _State(threading.local):
    def __init__(self):
        self.grad_enabled = True
        self.trace_ctx = None          # active program-capture context (jit/)
        self.amp_state = None          # active autocast state (amp/)
        self.static_record = False     # static.program_guard replay recording
        self.op_recorder = _metrics_recorder   # host-op instrumentation hook


_state = _State()


class _FanoutRecorder:
    """Fans one dispatch record out to several recorders (profiler + metrics
    active at once) without a second branch in apply_op."""

    __slots__ = ("recorders",)

    def __init__(self, recorders):
        self.recorders = tuple(recorders)

    def record(self, name, dt, **kw):
        for r in self.recorders:
            r.record(name, dt, **kw)


def compose_recorders(*recorders):
    """None-pruning composition: 0 -> None, 1 -> it, n -> fan-out."""
    recs = tuple(r for r in recorders if r is not None)
    if not recs:
        return None
    if len(recs) == 1:
        return recs[0]
    return _FanoutRecorder(recs)


def metrics_recorder():
    """The process-wide metrics recorder (None while telemetry is off)."""
    return _metrics_recorder


def set_metrics_recorder(rec):
    """Install/remove the process-wide metrics recorder.

    New threads inherit it on first dispatch-state access; the calling
    thread's slot is rewritten in place, preserving a profiler recorder
    stacked on top of the previous metrics recorder."""
    global _metrics_recorder
    prev = _metrics_recorder
    _metrics_recorder = rec
    cur = _state.op_recorder
    if isinstance(cur, _FanoutRecorder):
        keep = [r for r in cur.recorders if r is not prev]
    elif cur is None or cur is prev:
        keep = []
    else:
        keep = [cur]
    _state.op_recorder = compose_recorders(*keep, rec)


def grad_enabled() -> bool:
    return _state.grad_enabled


def set_grad_enabled(mode: bool) -> bool:
    prev = _state.grad_enabled
    _state.grad_enabled = mode
    return prev


def unwrap(x):
    """Tensor -> underlying array (trace-aware read)."""
    if isinstance(x, Tensor):
        tc = _state.trace_ctx
        if tc is not None:
            return tc.on_read(x)
        return x._buf
    return x


def _requires_grad(args) -> bool:
    if not _state.grad_enabled:
        return False
    for a in args:
        if isinstance(a, Tensor) and not a.stop_gradient:
            return True
    return False


def _wrap_out(arr, stop_gradient):
    t = Tensor(arr, stop_gradient=stop_gradient)
    return t


_FLOAT_KINDS = ("f", "V", "c")  # V covers bfloat16/fp8 extension dtypes


def apply_op(name: str, fn: Callable, *inputs, out_treedef_hint=None):
    """Run op `fn` over `inputs` (Tensors/arrays, the differentiable positions).

    Returns Tensor or tuple-of-Tensors mirroring fn's output structure.
    Attrs must be closed over inside `fn`.
    """
    rec = _state.op_recorder
    if rec is not None:
        t0 = time.perf_counter()
        try:
            return _apply_op_inner(name, fn, *inputs)
        finally:
            # facts the registry aggregates (autocast/tape/lift counts) are
            # re-derived here, on the instrumented path only, so the fast
            # path stays untouched
            rec.record(name, time.perf_counter() - t0,
                       amp=_state.amp_state is not None,
                       lifted=_state.trace_ctx is not None,
                       taped=_requires_grad(inputs))
    return _apply_op_inner(name, fn, *inputs)


def _apply_op_inner(name, fn, *inputs):
    tc = _state.trace_ctx
    if tc is not None and tc.mode == "echo":
        # break-stitched replay (jit/to_static.py): the compiled program
        # already ran; hand back shape-only placeholders with zero compute
        return tc.on_op_echo(name, inputs)
    arrays = tuple(unwrap(a) for a in inputs)
    if _state.amp_state is not None:
        from ..amp import maybe_cast_inputs
        arrays = maybe_cast_inputs(name, arrays)
    needs_grad = _requires_grad(inputs)

    if flags.flag("check_nan_inf"):
        out = _run_checked(name, fn, arrays, needs_grad, inputs)
        return out

    if needs_grad:
        from ..autograd.node import GradNode
        tc = _state.trace_ctx
        defer = ((tc is not None and getattr(tc, "mode", None) == "spy")
                 or flags.flag("eager_recompute_grad"))
        try:
            if defer:
                # capture spy pass (or FLAGS_eager_recompute_grad): don't hold
                # jax.vjp residuals per op — backward recomputes the vjp from
                # raw_fn+in_arrays one node at a time, so peak memory during
                # the eager discovery pass stays near the live-activation set
                # instead of sum-of-residuals (the round-2 capture OOM wall)
                outs, vjp_fn = fn(*arrays), None
            else:
                outs, vjp_fn = jax.vjp(fn, *arrays)
        except Exception as e:   # op-attributed errors (ref error summary)
            _attach_op_note(e, name, arrays)
            raise
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)
        node = GradNode(name, vjp_fn, inputs, outs_t, raw_fn=fn,
                        in_arrays=arrays, deferred=defer,
                        keep_arrays=_state.static_record)
        wrapped = []
        for i, o in enumerate(outs_t):
            diff = np.dtype(o.dtype).kind in _FLOAT_KINDS
            t = _wrap_out(o, stop_gradient=not diff)
            if diff:
                t._grad_node = node
                t._out_slot = i
            wrapped.append(t)
        node.set_outputs(wrapped)
        if _state.static_record:
            # the tape node already carries raw_fn/in_arrays; reuse it as
            # the replay entry (non-float outputs have no _grad_node link)
            for i, t in enumerate(wrapped):
                t._replay_node = (node, i)
        _notify_op(name, single, wrapped)
        return wrapped[0] if single else tuple(wrapped)
    else:
        try:
            outs = fn(*arrays)
        except Exception as e:
            _attach_op_note(e, name, arrays)
            raise
        single = not isinstance(outs, (tuple, list))
        wrapped = [_wrap_out(o, True)
                   for o in ((outs,) if single else outs)]
        if _state.static_record:
            _attach_replay(name, fn, inputs, arrays, wrapped)
        _notify_op(name, single, wrapped)
        return wrapped[0] if single else tuple(wrapped)


def _notify_op(name, single, wrapped):
    """Op-tape hook: the jit replay trace records each dispatch so the echo
    pass of a break-stitched signature can validate + placeholder it."""
    tc = _state.trace_ctx
    if tc is not None:
        hook = getattr(tc, "on_op", None)
        if hook is not None:
            hook(name, single, wrapped)


def _op_error_note(name, arrays):
    """One-line op attribution appended to dispatch failures (analog of the
    reference's error summary with op name + input metas)."""
    metas = ", ".join(
        f"{getattr(a, 'shape', ())}:{getattr(a, 'dtype', type(a).__name__)}"
        for a in arrays[:6])
    more = "..." if len(arrays) > 6 else ""
    return f"[paddle_tpu] raised while dispatching op '{name}' ({metas}{more})"


def _attach_op_note(e, name, arrays):
    note = _op_error_note(name, arrays)
    if hasattr(e, "add_note"):           # PEP 678, python >= 3.11
        e.add_note(note)
    else:                                # 3.10: fold into the message instead
        e.args = ((f"{e.args[0]}\n{note}",) + e.args[1:]) if e.args else (note,)


def _attach_replay(name, fn, inputs, arrays, wrapped):
    """static.program_guard: record replay linkage on EVERY output (incl.
    non-float/bool, which never get grad nodes) so Executor.run can re-execute
    the full op graph — the jaxpr-analog of a static Program block."""
    from ..autograd.node import GradNode
    rnode = GradNode(name, None, inputs, [t._buf for t in wrapped],
                     raw_fn=fn, in_arrays=arrays)
    for i, t in enumerate(wrapped):
        t._replay_node = (rnode, i)


def _run_checked(name, fn, arrays, needs_grad, inputs):
    """FLAGS_check_nan_inf debug path (fluid/eager/nan_inf_utils.cc analog)."""
    if needs_grad:
        from ..autograd.node import GradNode
        outs, vjp_fn = jax.vjp(fn, *arrays)
    else:
        outs, vjp_fn = fn(*arrays), None
    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(outs)
    for o in outs_t:
        if np.dtype(o.dtype).kind in _FLOAT_KINDS and not isinstance(o, jax.core.Tracer):
            bad = not bool(jnp.all(jnp.isfinite(o.astype(jnp.float32))))
            if bad:
                msg = f"nan/inf detected in output of op '{name}'"
                if flags.flag("check_nan_inf_level") == 0:
                    raise FloatingPointError(msg)
                print(f"[check_nan_inf] {msg}")  # graftlint: disable=no-adhoc-telemetry
    wrapped = []
    node = None
    if needs_grad:
        from ..autograd.node import GradNode
        node = GradNode(name, vjp_fn, inputs, outs_t)
    for i, o in enumerate(outs_t):
        diff = needs_grad and np.dtype(o.dtype).kind in _FLOAT_KINDS
        t = _wrap_out(o, stop_gradient=not diff)
        if diff:
            t._grad_node = node
            t._out_slot = i
        wrapped.append(t)
    if node is not None:
        node.set_outputs(wrapped)
    _notify_op(name, single, wrapped)
    return wrapped[0] if single else tuple(wrapped)


def defop(name: str):
    """Decorator: define an op by its array-level implementation.

    @defop("tanh")
    def tanh(x): return jnp.tanh(x)

    The wrapped callable takes Tensors (or anything array-like) positionally for
    differentiable inputs and keyword attrs, and routes through apply_op.
    """
    def deco(fn):
        def op(*args, **kwargs):
            if kwargs:
                f = lambda *arrs: fn(*arrs, **kwargs)
            else:
                f = fn
            return apply_op(name, f, *args)
        op.__name__ = name
        op.__qualname__ = name
        op.raw = fn
        return op
    return deco
