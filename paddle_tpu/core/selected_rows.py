"""SelectedRows — row-sparse gradient container (reference:
paddle/phi/core/selected_rows.h; produced by embedding backward when
`sparse=True` so a [V, D] table update touches only the looked-up rows).

The autograd engine carries it as a cotangent: SelectedRows + SelectedRows
concatenates (dedup is deferred to the consumer), mixing with a dense
array densifies. Optimizers apply it via their sparse path (SGD scatters
row updates; others densify — the reference restricts sparse grads to a
subset of optimizers the same way).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

__all__ = ["SelectedRows"]


class SelectedRows:
    def __init__(self, rows, values, height):
        self.rows = jnp.asarray(rows, jnp.int32).reshape(-1)
        self.values = jnp.asarray(values)          # [nnz, D]
        self.height = int(height)

    @property
    def shape(self):
        return [self.height, int(self.values.shape[-1])]

    @property
    def dtype(self):
        return self.values.dtype

    def is_selected_rows(self):
        return True

    def merge(self):
        """Coalesce duplicate rows (reference scatter::MergeAdd).
        Eager-only (concrete rows), so numpy unique gives an exact-size
        result — no padded entries that a consumer could misapply."""
        rows_np = np.asarray(self.rows)
        uniq, inv = np.unique(rows_np, return_inverse=True)
        summed = jnp.zeros((len(uniq), self.values.shape[-1]),
                           self.values.dtype).at[jnp.asarray(inv)].add(
            self.values)
        return SelectedRows(jnp.asarray(uniq.astype(np.int32)), summed,
                            self.height)

    def to_dense(self):
        dense = jnp.zeros((self.height, self.values.shape[-1]),
                          self.values.dtype)
        return dense.at[self.rows].add(self.values)

    def astype(self, dtype):
        return SelectedRows(self.rows, self.values.astype(dtype), self.height)

    def __add__(self, other):
        if isinstance(other, SelectedRows):
            return SelectedRows(jnp.concatenate([self.rows, other.rows]),
                                jnp.concatenate([self.values, other.values]),
                                self.height)
        arr = other._data if hasattr(other, "_data") else jnp.asarray(other)
        return arr.at[self.rows].add(self.values.astype(arr.dtype))

    def __radd__(self, other):
        return self.__add__(other)

    def numpy(self):
        return np.asarray(self.to_dense())

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, nnz={self.rows.shape[0]},"
                f" dim={self.values.shape[-1]})")
