"""Runtime flag registry.

Analog of the reference's FLAGS_* system (common/flags.cc, ~185 flags; python surface
paddle.set_flags/get_flags in python/paddle/base/framework.py:132). Flags are a plain
registry with env-var override (`FLAGS_<name>`), typed defaults, and change hooks so
subsystems can react (e.g. nan/inf checking toggling the debug dispatch path).
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable

_lock = threading.Lock()
_registry: dict[str, dict] = {}
_hooks: dict[str, list[Callable[[Any], None]]] = {}


def define_flag(name: str, default, help: str = ""):
    typ = type(default)
    value = default
    env = os.environ.get(f"FLAGS_{name}")
    if env is not None:
        value = _coerce(env, typ)
    _registry[name] = {"value": value, "default": default, "type": typ, "help": help}
    return value


def _coerce(v, typ):
    if typ is bool:
        return str(v).lower() in ("1", "true", "yes", "on")
    return typ(v)


def set_flags(flags: dict):
    with _lock:
        for name, value in flags.items():
            key = name[6:] if name.startswith("FLAGS_") else name
            if key not in _registry:
                raise KeyError(f"unknown flag {name!r}")
            entry = _registry[key]
            entry["value"] = _coerce(value, entry["type"])
            for hook in _hooks.get(key, ()):
                hook(entry["value"])


def get_flags(flags=None) -> dict:
    if flags is None:
        names = list(_registry)
    elif isinstance(flags, str):
        names = [flags]
    else:
        names = list(flags)
    out = {}
    for name in names:
        key = name[6:] if name.startswith("FLAGS_") else name
        out[f"FLAGS_{key}"] = _registry[key]["value"]
    return out


def flag(name: str):
    return _registry[name]["value"]


def on_change(name: str, hook: Callable[[Any], None]):
    _hooks.setdefault(name, []).append(hook)


# Core flags (subset of common/flags.cc relevant on TPU)
define_flag("check_nan_inf", False, "scan op outputs for nan/inf (debug dispatch path)")
define_flag("use_autotune", False,
            "time Pallas launch-config candidates and cache the best "
            "(ops/autotune.py)")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >=1: log only")
define_flag("low_precision_op_list", 0, "audit ops running in low precision")
define_flag("use_stride_kernel", True, "allow view/stride shortcuts where possible")
define_flag("eager_delete_tensor_gb", 0.0, "GC threshold (no-op: XLA manages memory)")
define_flag("tpu_matmul_precision", "highest",
            "jax matmul precision: default|high|highest. 'highest' makes fp32 "
            "matmuls true fp32 on the MXU (multi-pass bf16); bf16 inputs are "
            "unaffected, so bf16 training keeps full MXU throughput")
define_flag("log_level", 0, "VLOG-style verbosity for framework logging")
define_flag("flash_layout_direct", False,
            "flash attention reads [B,S,H,D] operands directly (no relayout "
            "copies) via in-kernel per-head lane slicing; measured slower on "
            "v5e at GPT-2 shapes, may win at other geometries")
define_flag("weight_only_use_kernel", True,
            "route weight_only_linear through the Pallas in-kernel-dequant "
            "matmul on TPU no-grad calls; False uses the XLA dequant "
            "formulation (r4 microbenches through the tunnel measured the "
            "two within noise of each other at the M=8 decode GEMM — "
            "benchmark on your own deployment)")
define_flag("eager_recompute_grad", False,
            "eager autograd stores op inputs only and recomputes each vjp at "
            "backward time (2x forward FLOPs, far lower peak memory); the "
            "to_static spy pass always runs in this mode")


def _apply_matmul_precision(value):
    """Wire tpu_matmul_precision to XLA. Without this, fp32 matmul/einsum
    silently run at bf16 precision on the TPU backend (one MXU pass)."""
    import jax

    jax.config.update("jax_default_matmul_precision",
                      None if value == "default" else value)


_apply_matmul_precision(flag("tpu_matmul_precision"))
on_change("tpu_matmul_precision", _apply_matmul_precision)
