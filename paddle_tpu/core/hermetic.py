"""Hermetic child-process environments (reference pattern: the fake-device /
CPU simulation contract in test/legacy_test/test_dist_base.py:957 — a CPU-bound
child must not attach the parent's accelerator runtime).

On this platform the TPU is reached through a PJRT plugin that a
``sitecustomize`` hook registers in EVERY python interpreter whose environment
carries the plugin's discovery variables — and the plugin ignores
``JAX_PLATFORMS=cpu``.  Any CPU-bound helper process (PS shard servers,
``launch --backend cpu`` workers, test subprocesses) that inherits those
variables will try to dial the accelerator tunnel at import time and, when the
tunnel is down, hang until a timeout.  A framework must produce its green
suite and its numbers even when the chip environment misbehaves, so every
CPU-bound spawn path routes through :func:`cpu_child_env`.
"""
import os

# Discovery/config variables of out-of-process accelerator plugins.  Removing
# the discovery var (`*_POOL_IPS`) is what prevents the sitecustomize hook from
# registering the plugin; the rest are its knobs, cleared for good measure.
ACCEL_PLUGIN_VARS = (
    "PALLAS_AXON_POOL_IPS",
    "PALLAS_AXON_REMOTE_COMPILE",
    "PALLAS_AXON_TPU_GEN",
    "AXON_POOL_SVC_OVERRIDE",
    "AXON_LOOPBACK_RELAY",
    "TPU_WORKER_HOSTNAMES",
)


def cpu_child_env(base=None, **extra):
    """Environment mapping for a child process that must run on XLA:CPU.

    Starts from ``base`` (default: ``os.environ``), strips accelerator-plugin
    discovery variables, forces ``JAX_PLATFORMS=cpu``, then applies ``extra``.
    """
    env = dict(os.environ if base is None else base)
    for var in ACCEL_PLUGIN_VARS:
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


def scrub_plugin_vars():
    """Strip accelerator-plugin variables from THIS process's environment so
    every descendant (however spawned) inherits a clean one.  Used by the test
    harness; returns the removed items for callers that want to restore them.
    """
    removed = {}
    for var in ACCEL_PLUGIN_VARS:
        if var in os.environ:
            removed[var] = os.environ.pop(var)
    return removed
