"""Random ops over the stateful Generator (reference: python/paddle/tensor/random.py).

Keys are split from the global generator whose state lives in a Tensor, so these ops
are capture-safe (fresh randomness per jitted step — see core/rng.py).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..core.rng import next_key
from ..core.dispatch import unwrap
from .creation import _norm_shape


def _dt(dtype):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else dtypes.get_default_dtype()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else next_key()
    dt = _dt(dtype)
    out = jax.random.uniform(key, _norm_shape(shape), dtype=jnp.float32,
                             minval=unwrap(min), maxval=unwrap(max))
    return Tensor(out.astype(dt))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def standard_normal(shape, dtype=None, name=None):
    out = jax.random.normal(next_key(), _norm_shape(shape), dtype=jnp.float32)
    return Tensor(out.astype(_dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = unwrap(mean), unwrap(std)
        sh = np.broadcast_shapes(np.shape(m), np.shape(s))
        out = jax.random.normal(next_key(), sh, dtype=jnp.float32) * s + m
        return Tensor(out)
    out = jax.random.normal(next_key(), _norm_shape(shape), dtype=jnp.float32)
    return Tensor((out * std + mean).astype(dtypes.get_default_dtype()))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype=None, name=None):
    key = jax.random.PRNGKey(seed) if seed else next_key()
    out = jax.random.normal(key, _norm_shape(shape), dtype=jnp.float32) * std + mean
    return Tensor(out.astype(_dt(dtype)))


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    out = jax.random.randint(next_key(), _norm_shape(shape), int(unwrap(low)), int(unwrap(high)))
    return Tensor(out.astype(dtypes.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    dt = dtype if dtype is not None else x.dtype
    return randint(low, high, tuple(x.shape), dt)


def randperm(n, dtype="int64", name=None):
    out = jax.random.permutation(next_key(), int(n))
    return Tensor(out.astype(dtypes.convert_dtype(dtype)))


def shuffle(x, name=None):
    a = unwrap(x)
    return Tensor(jax.random.permutation(next_key(), a, axis=0))


def bernoulli(x, name=None):
    p = unwrap(x)
    out = jax.random.bernoulli(next_key(), p.astype(jnp.float32))
    return Tensor(out.astype(p.dtype))


def bernoulli_(x, p=0.5, name=None):
    out = jax.random.bernoulli(next_key(), p, shape=tuple(x.shape))
    x._data = out.astype(x._data.dtype)
    return x


def standard_gamma(x, name=None):
    """Sample Gamma(alpha=x, scale=1) elementwise (reference
    tensor/random.py standard_gamma)."""
    alpha = unwrap(x)
    out = jax.random.gamma(next_key(), alpha.astype(jnp.float32))
    keep = jnp.issubdtype(alpha.dtype, jnp.floating)   # bfloat16-aware
    return Tensor(out.astype(alpha.dtype if keep else jnp.float32))


def poisson(x, name=None):
    lam = unwrap(x)
    out = jax.random.poisson(next_key(), lam.astype(jnp.float32))
    return Tensor(out.astype(lam.dtype))


def binomial(count, prob, name=None):
    n, p = unwrap(count), unwrap(prob)
    out = jax.random.binomial(next_key(), n.astype(jnp.float32), p.astype(jnp.float32))
    return Tensor(out.astype(jnp.int64))


def multinomial(x, num_samples=1, replacement=False, name=None):
    probs = unwrap(x)
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    if replacement:
        out = jax.random.categorical(next_key(), logits, axis=-1,
                                     shape=(num_samples,) + probs.shape[:-1])
        out = jnp.moveaxis(out, 0, -1)
    else:
        g = -jnp.log(-jnp.log(jax.random.uniform(next_key(), probs.shape) + 1e-20) + 1e-20)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else next_key()
    x._data = jax.random.uniform(key, tuple(x.shape), dtype=jnp.float32,
                                 minval=min, maxval=max).astype(x._data.dtype)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = (jax.random.normal(next_key(), tuple(x.shape), dtype=jnp.float32)
               * std + mean).astype(x._data.dtype)
    return x


def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(next_key(), tuple(x.shape), dtype=jnp.float32)
    x._data = (-jnp.log1p(-u) / lam).astype(x._data.dtype)
    return x


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    out = jax.random.normal(next_key(), _norm_shape(shape), dtype=jnp.float32) * std + mean
    return Tensor(jnp.exp(out).astype(dtypes.get_default_dtype()))


def cauchy_(x, loc=0, scale=1, name=None):
    u = jax.random.uniform(next_key(), tuple(x.shape), dtype=jnp.float32,
                           minval=1e-6, maxval=1 - 1e-6)
    x._data = (loc + scale * jnp.tan(jnp.pi * (u - 0.5))).astype(x._data.dtype)
    return x


def geometric_(x, probs=0.5, name=None):
    u = jax.random.uniform(next_key(), tuple(x.shape), dtype=jnp.float32,
                           minval=1e-6, maxval=1 - 1e-6)
    p = unwrap(probs) if hasattr(probs, "_data") else probs
    out = jnp.floor(jnp.log1p(-u) / jnp.log1p(-p)) + 1
    x._data = out.astype(x._data.dtype)
    return x


def log_normal_(x, mean=1.0, std=2.0, name=None):
    g = jax.random.normal(next_key(), tuple(x.shape), dtype=jnp.float32)
    x._data = jnp.exp(g * std + mean).astype(x._data.dtype)
    return x


def top_p_sampling(x, ps, threshold=None, topp_seed=None, seed=-1, k=0,
                   mode="truncated", return_top=False, name=None):
    """Nucleus (top-p) sampling over a probability matrix [batch, vocab]
    (reference: phi/kernels/top_p_sampling_kernel.h). Sorts descending, keeps
    the smallest prefix with cumulative prob >= ps, renormalizes, samples.
    Returns (scores, ids) like the reference."""
    probs = unwrap(x)
    p = unwrap(ps) if hasattr(ps, "_data") else jnp.asarray(ps, jnp.float32)
    p = p.reshape(-1, 1) if p.ndim <= 1 else p
    key = jax.random.PRNGKey(seed) if seed not in (-1, None) else next_key()

    sort_idx = jnp.argsort(-probs, axis=-1)
    sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
    cum = jnp.cumsum(sorted_p, axis=-1)
    # keep tokens whose *preceding* cumulative mass is < p (always >= 1 token)
    keep = (cum - sorted_p) < p
    filtered = jnp.where(keep, sorted_p, 0.0)
    filtered = filtered / jnp.sum(filtered, axis=-1, keepdims=True)
    choice = jax.random.categorical(key, jnp.log(jnp.maximum(filtered, 1e-30)),
                                    axis=-1)
    ids = jnp.take_along_axis(sort_idx, choice[:, None], axis=-1)
    scores = jnp.take_along_axis(probs, ids, axis=-1)
    return Tensor(scores), Tensor(ids.astype(jnp.int64))
