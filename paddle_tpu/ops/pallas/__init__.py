"""Pallas TPU kernels — the hand-written hot ops (SURVEY §7: flash attention,
paged/block attention, MoE dispatch, quantized matmul; everything else is XLA)."""
from . import flash_attention  # noqa: F401
from . import paged_attention  # noqa: F401
