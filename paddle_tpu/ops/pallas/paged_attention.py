"""Paged (block) KV-cache decode attention — the second Pallas TPU kernel
(reference capability: phi/kernels/fusion/gpu/block_multi_head_attention /
block_attn.h: paged KV blocks + per-sequence block tables).

TPU-native design: the KV cache lives in fixed-size pages
[num_pages, page_size, kv_heads, head_dim]; each sequence owns a row of the
block table. The kernel runs a (batch, page_slot) grid with the block table
scalar-prefetched, so each page's DMA address is computed *before* the body
runs (pltpu.PrefetchScalarGridSpec — the canonical TPU paged-attention
pattern). Online softmax state (m, l, acc) persists in VMEM scratch across the
sequential page_slot dimension; GQA q-head groups index their kv head directly
(no repeat materialization)."""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s, *,
            scale, page_size, n_slots, kv_heads, group):
    b = pl.program_id(0)
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    cl = cl_ref[b]
    n_valid = (cl + page_size - 1) // page_size

    @pl.when(s < n_valid)
    def _compute():
        # token validity inside this page
        tok = s * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = tok < cl                                   # [1, page_size]
        for h in range(kv_heads):
            # MXU operands stay in the input dtype (bf16 native mode);
            # softmax statistics and accumulation are f32
            q = q_ref[0, h * group:(h + 1) * group, :]
            k = k_ref[0, :, h, :]                          # [page, D]
            v = v_ref[0, :, h, :]
            sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=jax.lax.Precision.DEFAULT) * scale
            sc = jnp.where(valid, sc, NEG_INF)             # [group, page]
            row = slice(h * group, (h + 1) * group)
            m_prev = m_s[row, 0]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1))
            p = jnp.exp(sc - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_s[row, 0] = l_s[row, 0] * corr + jnp.sum(p, axis=1)
            acc_s[row, :] = acc_s[row, :] * corr[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.DEFAULT)
            m_s[row, 0] = m_new

    @pl.when(s == n_slots - 1)
    def _finish():
        denom = jnp.maximum(l_s[:, 0:1], 1e-30)
        o_ref[0] = (acc_s[:] / denom).astype(o_ref.dtype)


def _kernel_q(bt_ref, cl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
              m_s, l_s, acc_s, *, scale, page_size, n_slots, kv_heads, group):
    """int8-page variant (reference capability: block_multihead_attention's
    cache_k_quant_scales/cache_v_quant_scales, dynamic mode): pages carry
    int8 values + a per-(token, kv-head) f32 scale; the kernel dequantizes
    page tiles in VMEM right before the MXU dots, so HBM traffic (and page
    capacity) is ~half the bf16 cache's.

    Validation status: numerics proven against the dense reference in
    interpret mode (tests/test_kv_int8.py); Mosaic lowering of the int8
    VMEM loads has not yet run on a real chip (the tunnel was down for the
    whole r5 round) — the serving bench exercises it first thing on chip
    and its extras are isolated, so a lowering failure cannot take down the
    engine's bf16 path or the flagship metric."""
    b = pl.program_id(0)
    s = pl.program_id(1)

    @pl.when(s == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    cl = cl_ref[b]
    n_valid = (cl + page_size - 1) // page_size

    @pl.when(s < n_valid)
    def _compute():
        tok = s * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        valid = tok < cl                                   # [1, page_size]
        for h in range(kv_heads):
            q = q_ref[0, h * group:(h + 1) * group, :]
            k = (k_ref[0, :, h, :].astype(jnp.float32)
                 * ks_ref[0, :, h][:, None]).astype(q.dtype)
            v = (v_ref[0, :, h, :].astype(jnp.float32)
                 * vs_ref[0, :, h][:, None]).astype(q.dtype)
            sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=jax.lax.Precision.DEFAULT) * scale
            sc = jnp.where(valid, sc, NEG_INF)             # [group, page]
            row = slice(h * group, (h + 1) * group)
            m_prev = m_s[row, 0]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1))
            p = jnp.exp(sc - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_s[row, 0] = l_s[row, 0] * corr + jnp.sum(p, axis=1)
            acc_s[row, :] = acc_s[row, :] * corr[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.DEFAULT)
            m_s[row, 0] = m_new

    @pl.when(s == n_slots - 1)
    def _finish():
        denom = jnp.maximum(l_s[:, 0:1], 1e-30)
        o_ref[0] = (acc_s[:] / denom).astype(o_ref.dtype)


def _mq_step(q_ref, o_ref, m_s, l_s, acc_s, kv, cl, s, *, scale, page_size,
             n_slots, kv_heads, group, q_len):
    """Shared multi-query online-softmax body (speculative-decode
    verification): each sequence carries q_len query rows at consecutive
    positions, laid out kv-head-major ([B, H*q_len, D], row = qh*q_len + j)
    so every kv head's rows are one contiguous slice.  Each page is DMA'd
    ONCE per sequence and scored against all q_len rows — a per-row loop
    over the single-query kernel would stream the whole KV prefix q_len
    times.  Row j's causal horizon is ctx = cl + j (cl = context of row 0,
    itself included), enforced with a per-row position mask.  ``kv(h)``
    yields this page's (K, V) tile for kv head h, letting the bf16 and int8
    wrapper kernels differ only in how the tile is loaded."""
    @pl.when(s == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # pages holding anything the LAST query row may attend to
    n_valid = (cl + q_len - 1 + page_size - 1) // page_size
    rows = group * q_len

    @pl.when(s < n_valid)
    def _compute():
        tok = s * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 1)
        qpos = jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 0) % q_len
        valid = tok < cl + qpos                            # [rows, page]
        for h in range(kv_heads):
            q = q_ref[0, h * rows:(h + 1) * rows, :]
            k, v = kv(h)
            sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=jax.lax.Precision.DEFAULT) * scale
            sc = jnp.where(valid, sc, NEG_INF)             # [rows, page]
            row = slice(h * rows, (h + 1) * rows)
            m_prev = m_s[row, 0]
            m_new = jnp.maximum(m_prev, jnp.max(sc, axis=1))
            p = jnp.exp(sc - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_s[row, 0] = l_s[row, 0] * corr + jnp.sum(p, axis=1)
            acc_s[row, :] = acc_s[row, :] * corr[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.DEFAULT)
            m_s[row, 0] = m_new

    @pl.when(s == n_slots - 1)
    def _finish():
        denom = jnp.maximum(l_s[:, 0:1], 1e-30)
        o_ref[0] = (acc_s[:] / denom).astype(o_ref.dtype)


def _kernel_mq(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s,
               *, scale, page_size, n_slots, kv_heads, group, q_len):
    b = pl.program_id(0)
    s = pl.program_id(1)
    _mq_step(q_ref, o_ref, m_s, l_s, acc_s,
             lambda h: (k_ref[0, :, h, :], v_ref[0, :, h, :]),
             cl_ref[b], s, scale=scale, page_size=page_size, n_slots=n_slots,
             kv_heads=kv_heads, group=group, q_len=q_len)


def _kernel_mq_q(bt_ref, cl_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                 m_s, l_s, acc_s, *, scale, page_size, n_slots, kv_heads,
                 group, q_len):
    """int8-page multi-query variant: dequantizes page tiles in VMEM right
    before the MXU dots, exactly like _kernel_q."""
    b = pl.program_id(0)
    s = pl.program_id(1)
    dt = q_ref.dtype

    def kv(h):
        k = (k_ref[0, :, h, :].astype(jnp.float32)
             * ks_ref[0, :, h][:, None]).astype(dt)
        v = (v_ref[0, :, h, :].astype(jnp.float32)
             * vs_ref[0, :, h][:, None]).astype(dt)
        return k, v

    _mq_step(q_ref, o_ref, m_s, l_s, acc_s, kv, cl_ref[b], s, scale=scale,
             page_size=page_size, n_slots=n_slots, kv_heads=kv_heads,
             group=group, q_len=q_len)


def quantize_kv(x):
    """Per-(row, kv-head) symmetric int8 quantization of K/V rows
    [..., KVH, D] -> (int8 values, f32 scales [..., KVH])."""
    xf = x.astype(jnp.float32)
    s = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127).astype(jnp.int8)
    return q, s


@functools.partial(jax.jit, static_argnames=("scale",))
def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    *, k_scales=None, v_scales=None, scale=None):
    """Decode-step attention against a paged KV cache.

    q:             [B, H, D]       current-step queries
    k_pages/v_pages: [P, page_size, KVH, D]  (int8 when *_scales given)
    k_scales/v_scales: [P, page_size, KVH] f32 per-token-per-head scales
                   (int8 KV-cache mode; reference: incubate block_multihead_
                   attention.py:47-48 cache_*_quant_scales)
    block_tables:  [B, S] int32    physical page id per (sequence, slot)
    context_lens:  [B]   int32     tokens already in cache (incl. current)
    returns        [B, H, D]
    """
    B, H, D = q.shape
    P, page_size, KVH, _ = k_pages.shape
    S = block_tables.shape[1]
    assert H % KVH == 0, f"q heads {H} not a multiple of kv heads {KVH}"
    group = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    quant = k_scales is not None

    page_spec = pl.BlockSpec((1, page_size, KVH, D),
                             lambda b, s, bt, cl: (bt[b, s], 0, 0, 0))
    scale_spec = pl.BlockSpec((1, page_size, KVH),
                              lambda b, s, bt, cl: (bt[b, s], 0, 0))
    in_specs = [pl.BlockSpec((1, H, D), lambda b, s, bt, cl: (b, 0, 0)),
                page_spec, page_spec]
    operands = [block_tables, context_lens, q, k_pages, v_pages]
    if quant:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales, v_scales]
        kern = functools.partial(_kernel_q, scale=scale,
                                 page_size=page_size, n_slots=S,
                                 kv_heads=KVH, group=group)
    else:
        kern = functools.partial(_kernel, scale=scale, page_size=page_size,
                                 n_slots=S, kv_heads=KVH, group=group)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, S),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H, D), lambda b, s, bt, cl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, 1), jnp.float32),
            pltpu.VMEM((H, D), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=_interpret(),
    )(*operands)


def paged_attention_ref(q, k_pages, v_pages, block_tables, context_lens,
                        *, k_scales=None, v_scales=None, scale=None):
    """jnp reference (gathers pages densely) — golden for the kernel test."""
    B, H, D = q.shape
    P, page_size, KVH, _ = k_pages.shape
    S = block_tables.shape[1]
    group = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    out = []
    for b_i in range(B):
        pages = block_tables[b_i]                       # [S]
        k = k_pages[pages].reshape(S * page_size, KVH, D)
        v = v_pages[pages].reshape(S * page_size, KVH, D)
        if k_scales is not None:                        # int8 pages: dequant
            k = (k.astype(jnp.float32) *
                 k_scales[pages].reshape(S * page_size, KVH)[..., None])
            v = (v.astype(jnp.float32) *
                 v_scales[pages].reshape(S * page_size, KVH)[..., None])
        cl = context_lens[b_i]
        mask = jnp.arange(S * page_size) < cl
        qh = q[b_i].reshape(KVH, group, D).astype(jnp.float32)
        kh = jnp.moveaxis(k, 1, 0).astype(jnp.float32)  # [KVH, T, D]
        vh = jnp.moveaxis(v, 1, 0).astype(jnp.float32)
        sc = jnp.einsum("hgd,htd->hgt", qh * scale, kh)
        sc = jnp.where(mask[None, None, :], sc, NEG_INF)
        p = jax.nn.softmax(sc, axis=-1)
        out.append(jnp.einsum("hgt,htd->hgd", p, vh).reshape(H, D))
    return jnp.stack(out).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("scale",))
def paged_attention_multiquery(q, k_pages, v_pages, block_tables,
                               context_lens, *, k_scales=None, v_scales=None,
                               scale=None):
    """Verification attention: Q consecutive query positions per sequence
    against the paged KV cache (speculative decoding scores the pending
    token plus all drafts in ONE forward).

    q:             [B, Q, H, D]    row j sits at absolute position
                                   context_lens[b] - 1 + j
    context_lens:  [B] int32       cache tokens visible to row 0 (incl. its
                                   own just-written entry); row j's causal
                                   horizon is context_lens[b] + j
    k_pages/v_pages/block_tables/k_scales/v_scales: as paged_attention
    returns        [B, Q, H, D]

    The kernel streams each page once per sequence for all Q rows (the
    single-query kernel would pay the KV DMA Q times)."""
    B, Q, H, D = q.shape
    P, page_size, KVH, _ = k_pages.shape
    S = block_tables.shape[1]
    assert H % KVH == 0, f"q heads {H} not a multiple of kv heads {KVH}"
    group = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    quant = k_scales is not None
    # kv-head-major row layout: rows [h*group*Q, (h+1)*group*Q) belong to kv
    # head h, query position = row % Q
    qf = jnp.transpose(q, (0, 2, 1, 3)).reshape(B, H * Q, D)

    page_spec = pl.BlockSpec((1, page_size, KVH, D),
                             lambda b, s, bt, cl: (bt[b, s], 0, 0, 0))
    scale_spec = pl.BlockSpec((1, page_size, KVH),
                              lambda b, s, bt, cl: (bt[b, s], 0, 0))
    in_specs = [pl.BlockSpec((1, H * Q, D), lambda b, s, bt, cl: (b, 0, 0)),
                page_spec, page_spec]
    operands = [block_tables, context_lens, qf, k_pages, v_pages]
    if quant:
        in_specs += [scale_spec, scale_spec]
        operands += [k_scales, v_scales]
        kern = functools.partial(_kernel_mq_q, scale=scale,
                                 page_size=page_size, n_slots=S,
                                 kv_heads=KVH, group=group, q_len=Q)
    else:
        kern = functools.partial(_kernel_mq, scale=scale,
                                 page_size=page_size, n_slots=S,
                                 kv_heads=KVH, group=group, q_len=Q)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, S),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, H * Q, D),
                               lambda b, s, bt, cl: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((H * Q, 1), jnp.float32),
            pltpu.VMEM((H * Q, 1), jnp.float32),
            pltpu.VMEM((H * Q, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H * Q, D), q.dtype),
        interpret=_interpret(),
    )(*operands)
    return jnp.transpose(out.reshape(B, H, Q, D), (0, 2, 1, 3))


def paged_attention_multiquery_ref(q, k_pages, v_pages, block_tables,
                                   context_lens, *, k_scales=None,
                                   v_scales=None, scale=None):
    """jnp reference for the multi-query kernel (dense gather, per-row
    causal horizon ctx + j) — golden for the kernel test and the engine's
    CPU path."""
    B, Q, H, D = q.shape
    P, page_size, KVH, _ = k_pages.shape
    S = block_tables.shape[1]
    group = H // KVH
    if scale is None:
        scale = 1.0 / math.sqrt(D)
    out = []
    for b_i in range(B):
        pages = block_tables[b_i]
        k = k_pages[pages].reshape(S * page_size, KVH, D)
        v = v_pages[pages].reshape(S * page_size, KVH, D)
        if k_scales is not None:
            k = (k.astype(jnp.float32) *
                 k_scales[pages].reshape(S * page_size, KVH)[..., None])
            v = (v.astype(jnp.float32) *
                 v_scales[pages].reshape(S * page_size, KVH)[..., None])
        cl = context_lens[b_i]
        # row j attends tokens [0, cl + j)
        mask = (jnp.arange(S * page_size)[None, :]
                < cl + jnp.arange(Q)[:, None])             # [Q, T]
        qh = jnp.transpose(q[b_i], (1, 0, 2)).reshape(
            KVH, group, Q, D).astype(jnp.float32)
        kh = jnp.moveaxis(k, 1, 0).astype(jnp.float32)     # [KVH, T, D]
        vh = jnp.moveaxis(v, 1, 0).astype(jnp.float32)
        sc = jnp.einsum("hgqd,htd->hgqt", qh * scale, kh)
        sc = jnp.where(mask[None, None], sc, NEG_INF)
        p = jax.nn.softmax(sc, axis=-1)
        o = jnp.einsum("hgqt,htd->hgqd", p, vh)            # [KVH, g, Q, D]
        out.append(jnp.transpose(o.reshape(H, Q, D), (1, 0, 2)))
    return jnp.stack(out).astype(q.dtype)
