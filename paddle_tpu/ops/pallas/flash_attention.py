"""Flash attention Pallas TPU kernel (reference capability:
phi/kernels/gpu/flash_attn_kernel.cu:673 wrapping third_party/flashattn).

TPU-native blockwise online-softmax attention:
  forward — grid (B*H, Sq/BQ, Sk/BK); running (m, l, acc) in VMEM scratch
            persisted across the sequential k dimension; causal blocks skipped.
  backward — two kernels: dq (accumulate over k blocks) and dk/dv (accumulate
            over q blocks), recomputing P from the saved logsumexp; f32
            accumulation throughout; O(S) memory instead of O(S^2).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BQ = 128
BK = 128
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453


def _interpret() -> bool:
    # CPU has no Mosaic backend; run kernels in interpret mode (tests/CI)
    import jax
    return jax.default_backend() == "cpu"
NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
                causal, nk, bq, bk):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    run = True
    diag = False
    if causal:
        run = (j * bk) <= (i * bq + bq - 1)
        diag = (j * bk + bk - 1) > (i * bq)   # block crosses the diagonal

    def _body(masked):
        # MXU operands stay in the input dtype (bf16 native mode — f32
        # operands would force the slow multi-pass f32 MXU path); softmax
        # statistics and accumulation are f32. VPU-mindful: q is pre-scaled
        # by scale*log2(e) OUTSIDE the kernel, so scores arrive in the log2
        # domain — no (bq,bk)-wide scale multiply, and exp2 instead of exp.
        # Blocks fully below the causal diagonal skip the iota/compare/select
        # mask entirely (the hot interior is mask-free).
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        if masked:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_prev = m_s[:, 0]
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new[:, None])
        corr = jnp.exp2(m_prev - m_new)
        l_new = l_s[:, 0] * corr + jnp.sum(p, axis=1)
        acc_s[:] = acc_s[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        m_s[:] = jnp.broadcast_to(m_new[:, None], m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new[:, None], l_s.shape)

    if causal:
        @pl.when(run & diag)
        def _masked():
            _body(True)

        @pl.when(run & ~diag)
        def _interior():
            _body(False)
    else:
        _body(False)

    @pl.when(j == nk - 1)
    def _finish():
        l = l_s[:, 0]
        o_ref[0] = (acc_s[:] / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
        # running stats live in the log2 domain; stored lse stays natural
        lse_ref[0] = ((m_s[:, 0] + jnp.log2(jnp.maximum(l, 1e-30))) * LN2
                      )[:, None] + jnp.zeros_like(lse_ref[0])


def _check_divisible(Sq, Sk, D, bq=None, bk=None):
    bq, bk = bq or BQ, bk or BK
    if Sq % bq != 0 or Sk % bk != 0:
        raise ValueError(
            f"flash attention requires seq lengths divisible by ({bq}, {bk}) "
            f"(got q {Sq}, kv {Sk}); pad or use the XLA fallback")
    if D % 64 != 0:
        raise ValueError(f"flash attention requires head_dim % 64 == 0, got {D}")


def _kv_index(nh, nhk):
    """q-head grid index -> kv row index in a [B*nhk, Sk, D] tensor (GQA:
    kv head = q head // group, computed in the BlockSpec instead of
    materializing jnp.repeat'd K/V)."""
    rep = nh // nhk

    def index(b, i, j):
        return (b // nh) * nhk + (b % nh) // rep, j, 0

    return index


def _flash_fwd(q3, k3, v3, scale, causal, nh, nhk, bq=BQ, bk=BK):
    """q3 [B*nh, Sq, D], k3/v3 [B*nhk, Sk, D] -> (o [B*nh, Sq, D],
    lse [B*nh, Sq, 128])."""
    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    _check_divisible(Sq, Sk, D, bq, bk)
    nq, nk = Sq // bq, Sk // bk
    kvix = _kv_index(nh, nhk)
    # fold softmax scale + the exp->exp2 change of base into q once (fuses
    # into the producing op); the kernel then runs scale-free in log2 domain
    q3 = (q3.astype(jnp.float32) * (scale * LOG2E)).astype(q3.dtype)
    kern = functools.partial(_fwd_kernel, causal=causal, nk=nk, bq=bq, bk=bk)
    o, lse = pl.pallas_call(
        kern,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), kvix),
            pl.BlockSpec((1, bk, D), kvix),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, Sq, D), q3.dtype),
            jax.ShapeDtypeStruct((BH, Sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3)
    return o, lse


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref, dq_s, *,
               scale, causal, nk, bq, bk):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    run = True
    diag = False
    if causal:
        run = (j * bk) <= (i * bq + bq - 1)
        diag = (j * bk + bk - 1) > (i * bq)

    def _body(masked):
        # bf16 MXU operands, f32 softmax math/accumulation. Scores go
        # through the log2 domain like the forward: q is rescaled on its
        # small (bq, D) tile, so no (bq, bk)-wide multiplies remain.
        q = (q_ref[0].astype(jnp.float32) * (scale * LOG2E)).astype(
            q_ref.dtype)
        k = k_ref[0]
        do = do_ref[0]
        lse2 = lse_ref[0][:, 0] * LOG2E
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        if masked:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        delta = jnp.sum(do.astype(jnp.float32) *
                        o_ref[0].astype(jnp.float32), axis=1)
        ds = p * (dp - delta[:, None])
        dq_s[:] = dq_s[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale

    if causal:
        @pl.when(run & diag)
        def _masked():
            _body(True)

        @pl.when(run & ~diag)
        def _interior():
            _body(False)
    else:
        _body(False)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = dq_s[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref, dv_ref,
                dk_s, dv_s, *, scale, causal, nq, nt, bq, bk):
    j = pl.program_id(1)  # k block
    t = pl.program_id(2)  # combined (group q-head, q block) axis, sequential —
    i = t % nq            # dk/dv accumulate across the GQA group's q heads

    @pl.when(t == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    run = True
    diag = False
    if causal:
        run = (j * bk) <= (i * bq + bq - 1)
        diag = (j * bk + bk - 1) > (i * bq)

    def _body(masked):
        # bf16 MXU operands, f32 softmax math/accumulation; log2-domain
        # scores with q rescaled on its small tile (see _dq_kernel)
        q = q_ref[0]
        q2 = (q.astype(jnp.float32) * (scale * LOG2E)).astype(q_ref.dtype)
        k = k_ref[0]
        do = do_ref[0]
        lse2 = lse_ref[0][:, 0] * LOG2E
        s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        if masked:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp2(s - lse2[:, None])
        dv_s[:] = dv_s[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        dp = jax.lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT)
        delta = jnp.sum(do.astype(jnp.float32) *
                        o_ref[0].astype(jnp.float32), axis=1)
        ds = p * (dp - delta[:, None])
        dk_s[:] = dk_s[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.DEFAULT) * scale

    if causal:
        @pl.when(run & diag)
        def _masked():
            _body(True)

        @pl.when(run & ~diag)
        def _interior():
            _body(False)
    else:
        _body(False)

    @pl.when(t == nt - 1)
    def _finish():
        dk_ref[0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_s[:].astype(dv_ref.dtype)


def _flash_bwd(q3, k3, v3, o3, lse, do3, scale, causal, nh, nhk, bq=BQ,
               bk=BK):
    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    _check_divisible(Sq, Sk, D, bq, bk)
    nq, nk = Sq // bq, Sk // bk
    rep = nh // nhk
    kvix = _kv_index(nh, nhk)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal, nk=nk,
                          bq=bq, bk=bk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, D), kvix),
            pl.BlockSpec((1, bk, D), kvix),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bq, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, Sq, D), q3.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=_interpret(),
    )(q3, k3, v3, do3, o3, lse)

    # dk/dv: grid batch is the KV row; the combined t axis walks the GQA
    # group's q heads × q blocks sequentially so dk/dv accumulate the whole
    # group in VMEM scratch — no materialized head repeat anywhere.
    BHk = k3.shape[0]
    nt = rep * nq

    def qix(b, j, t):
        return (b // nhk) * nh + (b % nhk) * rep + t // nq, t % nq, 0

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal, nq=nq,
                          nt=nt, bq=bq, bk=bk),
        grid=(BHk, nk, nt),
        in_specs=[
            pl.BlockSpec((1, bq, D), qix),
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, bq, D), qix),
            pl.BlockSpec((1, bq, D), qix),
            pl.BlockSpec((1, bq, 128), lambda b, j, t: qix(b, j, t)[:2] + (0,)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BHk, Sk, D), k3.dtype),
            jax.ShapeDtypeStruct((BHk, Sk, D), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q3, k3, v3, do3, o3, lse)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash3(q3, k3, v3, scale, causal, nh, nhk, bq, bk):
    o, _ = _flash_fwd(q3, k3, v3, scale, causal, nh, nhk, bq, bk)
    return o


def _flash3_fwd(q3, k3, v3, scale, causal, nh, nhk, bq, bk):
    o, lse = _flash_fwd(q3, k3, v3, scale, causal, nh, nhk, bq, bk)
    return o, (q3, k3, v3, o, lse)


def _flash3_bwd(scale, causal, nh, nhk, bq, bk, res, do):
    q3, k3, v3, o, lse = res
    dq, dk, dv = _flash_bwd(q3, k3, v3, o, lse, do, scale, causal, nh, nhk,
                            bq, bk)
    return dq, dk, dv


_flash3.defvjp(_flash3_fwd, _flash3_bwd)


# Ordered by preference: cands[0] (the first divisibility+VMEM-viable entry)
# is the untuned default, so large blocks lead. Measured on v5e at
# B4/S1024/H12/D64 bf16: (512,1024) runs fwd+bwd 6x faster than (128,128) —
# fewer grid steps amortize MXU pipeline startup, and causal block-skipping
# still prunes the strictly-upper-triangle k blocks.
_BLOCK_CANDIDATES = [(512, 1024), (1024, 512), (512, 512), (1024, 1024),
                     (256, 512), (512, 256), (256, 256), (128, 256),
                     (256, 128), (512, 128), (128, 512), (128, 128)]


def _block_candidates(Sq, Sk, D, dtype):
    """Valid (bq, bk) choices: divisibility + a VMEM budget estimate
    (q/o/dq blocks bq*D, k/v bk*D, lse/m/l bq*128; f32 scratch; ~2x for
    pipelining double-buffering; PLUS the bq*bk score tiles — the _dkv
    backward materializes up to ~4 of s/p/dp/ds in f32, which dominates at
    the large blocks; keep under ~12MB of the 16MB/core VMEM)."""
    out = []
    for bq, bk in _BLOCK_CANDIDATES:
        if Sq % bq or Sk % bk:
            continue
        vmem = (3 * bq * D + 2 * bk * D + 3 * bq * 128) * 4 * 2 \
            + 4 * bq * bk * 4
        if vmem <= 12 * 1024 * 1024:
            out.append((bq, bk))
    return out or [(BQ, BK)]


def _pick_blocks(q3, k3, v3, causal):
    """Autotuned (bq, bk) for this shape (reference: autotune/switch_autotune
    picking conv/matmul algos). Tunes the forward kernel only — bwd shares
    the blocking — and only on concrete arrays outside any jit trace."""
    from .. import autotune as at
    BH, Sq, D = q3.shape
    Sk = k3.shape[1]
    cands = _block_candidates(Sq, Sk, D, q3.dtype)
    if len(cands) == 1:
        return cands[0]
    key = at.cache_key("flash_fwd", BH, Sq, Sk, D, q3.dtype, causal)

    def build(cfg):
        bq, bk = cfg

        def run(q, k, v):
            nh = nhk = 1  # timing proxy: head mapping doesn't affect blocking
            return _flash_fwd(q, k, v, 1.0, causal, nh, nhk, bq, bk)[0]
        return run

    # time on single-head views so tuning cost stays low
    return tuple(at.tune(key, cands, build, (q3[:1], k3[:1], v3[:1])))


def warm_autotune(q, k, v, causal=True):
    """Tune blocks for this [B, S, H, D] geometry from CONCRETE arrays.

    Dispatch wrappers call this before entering apply_op: inside apply_op the
    kernel only ever sees jax.vjp tracers, where tuning is impossible — but
    the cache lookup in _pick_blocks keys on static shapes, so one concrete
    warm call makes every traced call use the tuned blocks."""
    from .. import autotune as at
    if not at.enabled() or isinstance(q, jax.core.Tracer):
        return
    B, Sq, H, D = q.shape
    Hk = k.shape[2]
    try:
        q3 = jnp.moveaxis(q, 2, 1).reshape(B * H, Sq, D)
        k3 = jnp.moveaxis(k, 2, 1).reshape(B * Hk, k.shape[1], D)
        v3 = jnp.moveaxis(v, 2, 1).reshape(B * Hk, v.shape[1], D)
        _pick_blocks(q3, k3, v3, causal)
    except Exception:   # tuning is best-effort, never fails the op
        pass


# ---------------------------------------------------------------------------
# Layout-direct [B, S, H, D] kernels (MHA, nh == nhk).
#
# The 3D kernels above need [B*H, S, D] operands, which XLA materializes with
# physical layout copies around every custom call (~230us per qkv tensor per
# layer at GPT-2 b16 — profiled as the 'data formatting' bucket). These
# variants grid over (B, H/hb, Sq/bq, Sk/bk) with blocks (1, bq, hb, D) taken
# straight from the [B, S, H, D] array: the inner (hb, D) dims are contiguous
# in HBM so the DMA is dense, no transpose exists anywhere, and grid steps
# drop by hb. The head loop runs inside the kernel over VMEM slices.
# Blocks fully below the causal diagonal take a mask-free fast path (no
# iota/compare/select — pure VPU savings on the hot interior).
# ---------------------------------------------------------------------------

_B_BQ, _B_BK = 512, 512


def _bshd_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
                     scale, causal, nk, bq, bk, hb, d):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    run = True
    diag = False
    if causal:
        run = (j * bk) <= (i * bq + bq - 1)
        diag = (j * bk + bk - 1) > (i * bq)   # block crosses the diagonal

    def compute(masked):
        qf = q_ref[0]
        kf = k_ref[0]
        vf = v_ref[0]
        if masked:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            cm = rows >= cols
        for h in range(hb):
            q = qf[:, h * d:(h + 1) * d]
            k = kf[:, h * d:(h + 1) * d]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=jax.lax.Precision.DEFAULT) * scale
            if masked:
                s = jnp.where(cm, s, NEG_INF)
            m_prev = m_s[h, :, 0]
            l_prev = l_s[h, :, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=1)
            acc_s[h] = acc_s[h] * corr[:, None] + jax.lax.dot_general(
                p.astype(vf.dtype), vf[:, h * d:(h + 1) * d],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.DEFAULT)
            m_s[h] = jnp.broadcast_to(m_new[:, None], (bq, 128))
            l_s[h] = jnp.broadcast_to(l_new[:, None], (bq, 128))

    if causal:
        @pl.when(run & diag)
        def _masked():
            compute(True)

        @pl.when(run & ~diag)
        def _interior():
            compute(False)
    else:
        compute(False)

    @pl.when(j == nk - 1)
    def _finish():
        outs = []
        for h in range(hb):
            l = jnp.maximum(l_s[h, :, 0], 1e-30)
            outs.append((acc_s[h] / l[:, None]).astype(o_ref.dtype))
            lse_ref[h] = (m_s[h, :, 0] + jnp.log(l))[:, None] \
                + jnp.zeros_like(lse_ref[h])
        o_ref[0] = jnp.concatenate(outs, axis=1)


def _bshd_fwd(q, k, v, scale, causal, bq, bk, hb):
    """q/k/v [B, S, H, D] -> (o [B, S, H, D], lse [B*H, Sq, 128]).

    Operands are viewed as [B, S, H*D] (a free bitcast): blocks are dense
    (8,128)-tiled 2D slabs, per-head operands are static lane slices."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    nq, nk = Sq // bq, Sk // bk
    kern = functools.partial(_bshd_fwd_kernel, scale=scale, causal=causal,
                             nk=nk, bq=bq, bk=bk, hb=hb, d=D)
    o, lse = pl.pallas_call(
        kern,
        grid=(B, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, H * D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, H * D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, H * D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, H * D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((hb, bq, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Sq, H * D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Sq, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((hb, bq, 128), jnp.float32),
            pltpu.VMEM((hb, bq, 128), jnp.float32),
            pltpu.VMEM((hb, bq, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q.reshape(B, Sq, H * D), k.reshape(B, Sk, H * D),
      v.reshape(B, Sk, H * D))
    return o.reshape(B, Sq, H, D), lse


def _bshd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref, dq_s,
                    *, scale, causal, nk, bq, bk, hb, d):
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    run = True
    diag = False
    if causal:
        run = (j * bk) <= (i * bq + bq - 1)
        diag = (j * bk + bk - 1) > (i * bq)

    def compute(masked):
        qf, kf, vf, dof, of = q_ref[0], k_ref[0], v_ref[0], do_ref[0], o_ref[0]
        if masked:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            cm = rows >= cols
        for h in range(hb):
            sl = slice(h * d, (h + 1) * d)
            q, k, do = qf[:, sl], kf[:, sl], dof[:, sl]
            lse = lse_ref[h][:, 0]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=jax.lax.Precision.DEFAULT) * scale
            if masked:
                s = jnp.where(cm, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            dp = jax.lax.dot_general(do, vf[:, sl], (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=jax.lax.Precision.DEFAULT)
            delta = jnp.sum(do.astype(jnp.float32) *
                            of[:, sl].astype(jnp.float32), axis=1)
            ds = p * (dp - delta[:, None])
            dq_s[h] = dq_s[h] + jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.DEFAULT) * scale

    if causal:
        @pl.when(run & diag)
        def _masked():
            compute(True)

        @pl.when(run & ~diag)
        def _interior():
            compute(False)
    else:
        compute(False)

    @pl.when(j == nk - 1)
    def _finish():
        dq_ref[0] = jnp.concatenate(
            [dq_s[h].astype(dq_ref.dtype) for h in range(hb)], axis=1)


def _bshd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dk_ref,
                     dv_ref, dk_s, dv_s, *, scale, causal, nq, bq, bk, hb, d):
    j = pl.program_id(1)   # k block
    i = pl.program_id(2)   # q block (sequential accumulation axis)

    @pl.when(i == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    run = True
    diag = False
    if causal:
        run = (j * bk) <= (i * bq + bq - 1)
        diag = (j * bk + bk - 1) > (i * bq)

    def compute(masked):
        qf, kf, vf, dof, of = q_ref[0], k_ref[0], v_ref[0], do_ref[0], o_ref[0]
        if masked:
            rows = i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            cm = rows >= cols
        for h in range(hb):
            sl = slice(h * d, (h + 1) * d)
            q, k, do = qf[:, sl], kf[:, sl], dof[:, sl]
            lse = lse_ref[h][:, 0]
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32,
                                    precision=jax.lax.Precision.DEFAULT) * scale
            if masked:
                s = jnp.where(cm, s, NEG_INF)
            p = jnp.exp(s - lse[:, None])
            dv_s[h] = dv_s[h] + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.DEFAULT)
            dp = jax.lax.dot_general(do, vf[:, sl], (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32,
                                     precision=jax.lax.Precision.DEFAULT)
            delta = jnp.sum(do.astype(jnp.float32) *
                            of[:, sl].astype(jnp.float32), axis=1)
            ds = p * (dp - delta[:, None])
            dk_s[h] = dk_s[h] + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.DEFAULT) * scale

    if causal:
        @pl.when(run & diag)
        def _masked():
            compute(True)

        @pl.when(run & ~diag)
        def _interior():
            compute(False)
    else:
        compute(False)

    @pl.when(i == nq - 1)
    def _finish():
        dk_ref[0] = jnp.concatenate(
            [dk_s[h].astype(dk_ref.dtype) for h in range(hb)], axis=1)
        dv_ref[0] = jnp.concatenate(
            [dv_s[h].astype(dv_ref.dtype) for h in range(hb)], axis=1)


def _bshd_bwd(q, k, v, o, lse, do, scale, causal, bq, bk, hb):
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    nq, nk = Sq // bq, Sk // bk
    q2 = q.reshape(B, Sq, H * D)
    k2 = k.reshape(B, Sk, H * D)
    v2 = v.reshape(B, Sk, H * D)
    o2 = o.reshape(B, Sq, H * D)
    do2 = do.reshape(B, Sq, H * D)
    qspec = pl.BlockSpec((1, bq, H * D), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, bk, H * D), lambda b, i, j: (b, j, 0))
    lspec = pl.BlockSpec((hb, bq, 128), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(_bshd_dq_kernel, scale=scale, causal=causal, nk=nk,
                          bq=bq, bk=bk, hb=hb, d=D),
        grid=(B, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, qspec, lspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((B, Sq, H * D), q.dtype),
        scratch_shapes=[pltpu.VMEM((hb, bq, D), jnp.float32)],
        interpret=_interpret(),
    )(q2, k2, v2, do2, o2, lse)
    qspec_t = pl.BlockSpec((1, bq, H * D), lambda b, j, i: (b, i, 0))
    kspec_t = pl.BlockSpec((1, bk, H * D), lambda b, j, i: (b, j, 0))
    lspec_t = pl.BlockSpec((hb, bq, 128), lambda b, j, i: (b, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_bshd_dkv_kernel, scale=scale, causal=causal,
                          nq=nq, bq=bq, bk=bk, hb=hb, d=D),
        grid=(B, nk, nq),
        in_specs=[qspec_t, kspec_t, kspec_t, qspec_t, qspec_t, lspec_t],
        out_specs=[kspec_t, kspec_t],
        out_shape=[
            jax.ShapeDtypeStruct((B, Sk, H * D), k.dtype),
            jax.ShapeDtypeStruct((B, Sk, H * D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((hb, bk, D), jnp.float32),
            pltpu.VMEM((hb, bk, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q2, k2, v2, do2, o2, lse)
    return (dq.reshape(B, Sq, H, D), dk.reshape(B, Sk, H, D),
            dv.reshape(B, Sk, H, D))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bshd(q, k, v, scale, causal, bq, bk, hb):
    o, _ = _bshd_fwd(q, k, v, scale, causal, bq, bk, hb)
    return o


def _flash_bshd_fwd(q, k, v, scale, causal, bq, bk, hb):
    o, lse = _bshd_fwd(q, k, v, scale, causal, bq, bk, hb)
    return o, (q, k, v, o, lse)


def _flash_bshd_bwd(scale, causal, bq, bk, hb, res, do):
    q, k, v, o, lse = res
    return _bshd_bwd(q, k, v, o, lse, do, scale, causal, bq, bk, hb)


_flash_bshd.defvjp(_flash_bshd_fwd, _flash_bshd_bwd)


def _bshd_config(B, Sq, Sk, H, D, dtype):
    """(bq, bk, hb) for the layout-direct path, or None if it doesn't apply.

    Mosaic requires the last two block dims to be (8,128)-divisible OR equal
    to the array dims, so the head axis cannot be partially blocked: hb == H
    always, and the path only applies when a whole-H block fits VMEM.
    Estimate: q/o blocks bq*H*D, k/v bk*H*D (x2 double-buffer), f32 scratch
    H*bq*(2*128+D), f32 score tiles ~3*bq*bk per live head."""
    itemsize = jnp.dtype(dtype).itemsize
    for bq, bk in ((_B_BQ, _B_BK), (256, 512), (256, 256), (128, 256),
                   (128, 128)):
        if Sq % bq or Sk % bk:
            continue
        # the unrolled per-head loop keeps ~1.5 f32 score tiles live PER HEAD
        # (measured: (256,512,H=12) hit 17.25M scoped vmem vs a 16M limit
        # when the estimate ignored this term)
        vmem = (2 * (2 * bq + 2 * bk) * H * D * itemsize
                + H * bq * (2 * 128 + D) * 4
                + int(1.5 * H * bq * bk * 4))
        if vmem <= 12 * 1024 * 1024:
            return bq, bk, H
    return None


def flash_attention_bshd(q, k, v, causal=True, scale=None):
    """[B, S, H, D] flash attention. MHA (nh == nhk) uses the layout-direct
    kernels (no transposes, dense DMA); GQA falls back to the [B*H, S, D]
    kernels whose BlockSpecs index kv-head = q-head // group — K/V are never
    repeated in HBM (at Llama-3-8B's 32q/8kv that repeat would be 4x KV
    memory). Block sizes come from the autotuner cache when
    FLAGS_use_autotune is set."""
    B, Sq, H, D = q.shape
    Hk = k.shape[2]
    if H % Hk != 0:
        raise ValueError(f"q heads ({H}) must be a multiple of kv heads ({Hk})")
    s = scale if scale is not None else 1.0 / math.sqrt(D)
    from ...core import flags as _flags
    if H == Hk and _flags.flag("flash_layout_direct"):
        # opt-in: skips the [B*H,S,D] relayout copies, but the per-head lane
        # slicing inside the kernel costs more than the copies save on v5e at
        # GPT-2 shapes (measured 1.18 vs 0.93 ms/layer fwd) — off by default
        cfg = _bshd_config(B, Sq, k.shape[1], H, D, q.dtype)
        if cfg is not None:
            bq, bk, hb = cfg
            return _flash_bshd(q, k, v, s, causal, bq, bk, hb)
    q3 = jnp.moveaxis(q, 2, 1).reshape(B * H, Sq, D)
    k3 = jnp.moveaxis(k, 2, 1).reshape(B * Hk, k.shape[1], D)
    v3 = jnp.moveaxis(v, 2, 1).reshape(B * Hk, v.shape[1], D)
    bq, bk = _pick_blocks(q3, k3, v3, causal)
    o3 = _flash3(q3, k3, v3, s, causal, H, Hk, bq, bk)
    return jnp.moveaxis(o3.reshape(B, H, Sq, D), 1, 2)


def supported(q_shape, kv_shape=None, dtype=None) -> bool:
    """Single dispatch predicate for the Pallas path ([B, S, H, D] layouts)."""
    B, S, H, D = q_shape
    ok = (S % BQ == 0) and (D % 64 == 0)
    if kv_shape is not None:
        Sk, Hk = kv_shape[1], kv_shape[2]
        ok = ok and (Sk % BK == 0) and (Hk > 0) and (H % Hk == 0)
    return ok
