"""Weight-only quantized matmul Pallas kernel (reference capability:
phi/kernels/gpu/weight_only_linear_kernel.cu + cutlass fpA_intB gemm).

Decode-time linear layers are WEIGHT-BANDWIDTH bound: y = x @ W with tiny M
streams the whole weight matrix from HBM per token. Storing W as int8/int4
halves/quarters that stream — but only if the bf16 copy is never
materialized. This kernel reads int8 (or packed int4) tiles into VMEM,
dequantizes per tile on the VPU, and feeds the MXU directly; the f32
accumulator applies the per-output-channel scale in the epilogue.

grid (N/bn, K/bk): k is the fast (sequential) axis so the f32 accumulator
lives in VMEM scratch across k steps; x [M, bk] tiles are small (decode M),
weight tiles stream once.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BK = 256
BN = 256


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def _kernel(x_ref, qw_ref, s_ref, o_ref, acc_s, *, nk, int4, out_dtype):
    k = pl.program_id(1)

    @pl.when(k == 0)
    def _init():
        acc_s[:] = jnp.zeros_like(acc_s)

    q = qw_ref[...]
    if int4:
        lo = (q << 4).astype(jnp.int8) >> 4      # sign-extend low nibble
        hi = q >> 4                              # arithmetic shift high
        # packed rows [bk//2, bn] -> interleaved [bk, bn] (row 2i from lo,
        # row 2i+1 from hi) matching the packer in quantization/weight_only
        w = jnp.stack([lo, hi], axis=1).reshape(-1, q.shape[-1])
    else:
        w = q
    wt = w.astype(jnp.bfloat16)                  # tile-local dequant (VMEM)
    acc_s[:] = acc_s[:] + jax.lax.dot_general(
        x_ref[...], wt, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)

    @pl.when(k == nk - 1)
    def _finish():
        o_ref[...] = (acc_s[:] * s_ref[0].astype(jnp.float32)[None, :]
                      ).astype(out_dtype)


def quant_matmul(x, qw, scale, *, int4=False, bk=BK, bn=BN):
    """x [M, K] float/bf16, qw int8 [K, N] (or packed [K//2, N] for int4),
    scale f32 [N] -> y [M, N] in x.dtype."""
    M, K = x.shape
    N = qw.shape[1]
    Kq = qw.shape[0] * (2 if int4 else 1)
    if Kq != K:
        raise ValueError(f"weight K {Kq} != x K {K}")
    if K % bk or N % bn:
        raise ValueError(f"shapes must divide blocks ({bk},{bn})")
    Mp = max(8, M)           # sublane-pad tiny decode batches
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    nk, nn = K // bk, N // bn
    wk = bk // 2 if int4 else bk
    out = pl.pallas_call(
        functools.partial(_kernel, nk=nk, int4=int4, out_dtype=x.dtype),
        grid=(nn, nk),
        in_specs=[
            pl.BlockSpec((Mp, bk), lambda n, k: (0, k)),
            pl.BlockSpec((wk, bn), lambda n, k: (k, n)),
            pl.BlockSpec((1, bn), lambda n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((Mp, bn), lambda n, k: (0, n)),
        out_shape=jax.ShapeDtypeStruct((Mp, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((Mp, bn), jnp.float32)],
        interpret=_interpret(),
    )(x, qw, scale.reshape(1, N))
    return out[:M]


def supported(M, K, N, int4=False, bk=BK, bn=BN):
    return K % bk == 0 and N % bn == 0
