"""Kernel autotuner (reference: paddle/phi/kernels/autotune/ — cache.h
AutoTuneCache keyed by algorithm+shape hash, switch_autotune.h controlling
when tuning runs).

TPU-native: candidates are Pallas launch configs (block sizes), timed with
real compiled executions on the live device and memoized per
(op, static-shape/dtype) key, with optional on-disk persistence so a
relaunched job skips re-tuning (the reference persists via its cache
serialization). Tuning only ever happens on CONCRETE arrays — under a jit
trace the cached (or default) config is used, so autotuning never bakes
timing side effects into a compiled program."""
from __future__ import annotations

import json
import os
import threading
import time

import jax

from ..core import flags

if "use_autotune" not in flags._registry:   # normally defined in core/flags
    flags.define_flag("use_autotune", False,
                      "time Pallas launch-config candidates and cache the "
                      "best")

_lock = threading.Lock()
_cache: dict[str, dict] = {}
_loaded = False
_DISK = os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE",
                       os.path.expanduser("~/.cache/paddle_tpu/autotune.json"))


def _load_disk():
    global _loaded
    if _loaded:
        return
    _loaded = True
    try:
        with open(_DISK) as f:
            _cache.update(json.load(f))
    except (OSError, ValueError):
        pass


def _save_disk():
    try:
        os.makedirs(os.path.dirname(_DISK), exist_ok=True)
        with open(_DISK, "w") as f:
            json.dump(_cache, f)
    except OSError:
        pass


def cache_key(op: str, *parts) -> str:
    return f"{op}|" + "|".join(str(p) for p in parts)


def lookup(key: str):
    _load_disk()
    with _lock:
        hit = _cache.get(key)
    return tuple(hit) if isinstance(hit, list) else hit


def enabled() -> bool:
    return bool(flags.flag("use_autotune"))


def _concrete(args) -> bool:
    return not any(isinstance(a, jax.core.Tracer) for a in args)


def tune(key: str, candidates, build, args, iters=3):
    """Pick the fastest candidate config for `key`.

    build(cfg) -> callable(*args). Returns the cached config when present;
    times candidates only when autotune is enabled AND args are concrete
    (never inside a jit trace); otherwise returns candidates[0]."""
    hit = lookup(key)
    if hit is not None:
        return hit
    if not enabled() or not _concrete(args):
        return candidates[0]
    best, best_t = None, float("inf")
    for cfg in candidates:
        try:
            fn = build(cfg)
            jax.block_until_ready(fn(*args))       # compile + warm
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(*args)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / iters
        except Exception:
            continue                                # invalid config: skip
        if dt < best_t:
            best, best_t = cfg, dt
    if best is None:
        best = candidates[0]
    with _lock:
        _cache[key] = list(best) if isinstance(best, tuple) else best
        _save_disk()
    return best


def clear():
    with _lock:
        _cache.clear()
