"""Linear algebra ops (reference: python/paddle/tensor/linalg.py; MXU-heavy ops)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if p == np.inf or p == float("inf"):
            ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == -np.inf or p == float("-inf"):
            ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        if ax is None:
            a = a.reshape(-1)
            ax = 0
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=ax, keepdims=keepdim), 1.0 / p)
    return apply_op("p_norm", f, x)


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def f(a):
        return jnp.linalg.norm(a, ord=None if p == "fro" else p, axis=tuple(axis), keepdims=keepdim)
    return apply_op("matrix_norm", f, x)


def dist(x, y, p=2, name=None):
    return apply_op("dist", lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p), x, y)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:  # paddle default: first axis with dim 3
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply_op("cross", f, x, y)


def cholesky(x, upper=False, name=None):
    def f(a):
        L = jnp.linalg.cholesky(a)
        return jnp.swapaxes(L, -1, -2).conj() if upper else L
    return apply_op("cholesky", f, x)


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply_op("cholesky_solve", f, x, y)


def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, x)


inv = inverse


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), x)


def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply_op("triangular_solve", f, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    a, b = unwrap(x), unwrap(y)
    sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
    return Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv)


def qr(x, mode="reduced", name=None):
    out = jnp.linalg.qr(unwrap(x), mode=mode)
    if mode == "r":
        return Tensor(out)
    return Tensor(out[0]), Tensor(out[1])


def svd(x, full_matrices=False, name=None):
    u, s, vh = jnp.linalg.svd(unwrap(x), full_matrices=full_matrices)
    return Tensor(u), Tensor(s), Tensor(jnp.swapaxes(vh, -1, -2).conj())


def svdvals(x, name=None):
    return Tensor(jnp.linalg.svd(unwrap(x), compute_uv=False))


def eig(x, name=None):
    w, v = jnp.linalg.eig(np.asarray(unwrap(x)))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    w, v = jnp.linalg.eigh(unwrap(x), UPLO=UPLO)
    return Tensor(w), Tensor(v)


def eigvals(x, name=None):
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(unwrap(x)))))


def eigvalsh(x, UPLO="L", name=None):
    return Tensor(jnp.linalg.eigvalsh(unwrap(x), UPLO=UPLO))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return Tensor(jnp.linalg.matrix_rank(unwrap(x), rtol=tol))


def matrix_power(x, n, name=None):
    return apply_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), x)


def slogdet(x, name=None):
    s, ld = jnp.linalg.slogdet(unwrap(x))
    return Tensor(jnp.stack([s, ld]))


def det(x, name=None):
    return apply_op("det", jnp.linalg.det, x)


def multi_dot(x, name=None):
    return apply_op("multi_dot", lambda *arrs: jnp.linalg.multi_dot(arrs), *x)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = unwrap(fweights) if fweights is not None else None
    aw = unwrap(aweights) if aweights is not None else None
    return apply_op("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                                             fweights=fw, aweights=aw), x)


def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    a = np.asarray(unwrap(input))
    rng = None if (min == 0 and max == 0) else (min, max)
    hist, _ = np.histogram(a, bins=bins, range=rng,
                           weights=np.asarray(unwrap(weight)) if weight is not None else None,
                           density=density)
    return Tensor(jnp.asarray(hist if density else hist.astype(np.int64)))


def bincount(x, weights=None, minlength=0, name=None):
    a = unwrap(x)
    w = unwrap(weights) if weights is not None else None
    length = int(builtins_max(int(jnp.max(a)) + 1 if a.size else 0, minlength))
    out = jnp.zeros((length,), jnp.int64 if w is None else w.dtype)
    out = out.at[a].add(1 if w is None else w)
    return Tensor(out)


import builtins
builtins_max = builtins.max


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        eye = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(eye, a.shape[:-2] + (m, m))
        for i in range(n):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[..., i].set(1.0)
            ti = t[..., i:i+1, None]
            q = q - ti * jnp.einsum("...ij,...j,...k->...ik", q, v, v)
        return q[..., :, :n]
    return apply_op("householder_product", f, x, tau)
