"""Shape/layout manipulation ops (reference: python/paddle/tensor/manipulation.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..core.dispatch import apply_op, unwrap


def _shape_arg(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    out = []
    for s in (shape if isinstance(shape, (list, tuple)) else [shape]):
        out.append(int(unwrap(s)) if not isinstance(s, (int, np.integer)) else int(s))
    return tuple(out)


def cast(x, dtype):
    dt = dtypes.convert_dtype(dtype)
    return apply_op("cast", lambda a: a.astype(dt), x)


def reshape(x, shape, name=None):
    sh = _shape_arg(shape)
    return apply_op("reshape", lambda a: jnp.reshape(a, sh), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data, x._grad_node, x._out_slot = out._data, out._grad_node, out._out_slot
    x.stop_gradient = out.stop_gradient if not x.stop_gradient else x.stop_gradient
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def transpose(x, perm, name=None):
    p = tuple(int(i) for i in perm)
    return apply_op("transpose", lambda a: jnp.transpose(a, p), x)


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply_op("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), x)

transpose_ = swapaxes


def t(x, name=None):
    def f(a):
        return a if a.ndim < 2 else a.T
    return apply_op("t", f, x)


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = axis if isinstance(axis, (list, tuple)) else [axis]
        axes = tuple(ax % a.ndim for ax in axes)
        axes = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply_op("squeeze", f, x)


def unsqueeze(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    axes = [int(unwrap(a)) for a in axes]
    def f(a):
        out = a
        for ax in sorted(ax if ax >= 0 else ax + out.ndim + 1 for ax in axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply_op("unsqueeze", f, x)


def concat(x, axis=0, name=None):
    ax = int(unwrap(axis))
    return apply_op("concat", lambda *arrs: jnp.concatenate(arrs, axis=ax), *x)


def stack(x, axis=0, name=None):
    return apply_op("stack", lambda *arrs: jnp.stack(arrs, axis=axis), *x)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(unwrap(axis))
    def f(a):
        n = a.shape[ax]
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=ax))
        secs = [int(s) for s in num_or_sections]
        neg = [i for i, s in enumerate(secs) if s < 0]
        if neg:
            secs[neg[0]] = n - builtins_sum(s for s in secs if s >= 0)
        points = np.cumsum(secs)[:-1].tolist()
        return tuple(jnp.split(a, points, axis=ax))
    out = apply_op("split", f, x)
    return list(out) if isinstance(out, tuple) else [out]


import builtins
builtins_sum = builtins.sum


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(input, axis=0, name=None):
    n = input.shape[axis]
    outs = split(input, n, axis)
    return [squeeze(o, axis) for o in outs]


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        if nd == 0:
            return a.reshape(1)
        s0 = start_axis % nd
        s1 = stop_axis % nd
        new_shape = a.shape[:s0] + (-1,) + a.shape[s1 + 1:]
        return a.reshape(new_shape)
    return apply_op("flatten", f, x)


def tile(x, repeat_times, name=None):
    reps = _shape_arg(repeat_times)
    return apply_op("tile", lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    sh = list(_shape_arg(shape))
    def f(a):
        full = list(sh)
        # -1 means keep original dim (paddle semantics)
        offset = len(full) - a.ndim
        for i in range(len(full)):
            if full[i] == -1:
                full[i] = a.shape[i - offset]
        return jnp.broadcast_to(a, tuple(full))
    return apply_op("expand", f, x)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_tensors(input, name=None):
    shapes = [tuple(t.shape) for t in input]
    target = np.broadcast_shapes(*shapes)
    return [expand(t, list(target)) for t in input]


def flip(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    return apply_op("flip", lambda a: jnp.flip(a, axis=tuple(axes)), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    return apply_op("roll", lambda a: jnp.roll(a, shifts, axis=axis), x)


def gather(x, index, axis=0, name=None):
    idx = unwrap(index)
    ax = int(unwrap(axis))
    def f(a):
        i = idx.reshape(-1) if idx.ndim > 1 else idx
        return jnp.take(a, i, axis=ax)
    return apply_op("gather", f, x)


def gather_nd(x, index, name=None):
    idx = unwrap(index)
    def f(a):
        ix = tuple(jnp.moveaxis(idx, -1, 0))
        return a[ix]
    return apply_op("gather_nd", f, x)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    idx = unwrap(indices)
    def f(a):
        i = idx
        if broadcast:
            tgt = list(np.broadcast_shapes(tuple(a.shape[:axis] + (1,) + a.shape[axis+1:]),
                                           tuple(i.shape)))
            tgt[axis] = i.shape[axis]
            i = jnp.broadcast_to(i, tgt)
        return jnp.take_along_axis(a, i, axis=axis)
    return apply_op("take_along_axis", f, arr)


def put_along_axis(arr, indices, values, axis, reduce="assign", include_self=True,
                   broadcast=True, name=None):
    idx = unwrap(indices)
    def f(a, v):
        v = jnp.broadcast_to(jnp.asarray(v, a.dtype), idx.shape)
        if reduce == "assign":
            return jnp.put_along_axis(a, idx, v, axis=axis, inplace=False)
        ax_idx = [jnp.broadcast_to(
            jnp.arange(idx.shape[d]).reshape([-1 if i == d else 1 for i in range(idx.ndim)]),
            idx.shape) for d in range(idx.ndim)]
        ax_idx[axis] = idx
        ix = tuple(ax_idx)
        if reduce in ("add", "sum"):
            return a.at[ix].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[ix].multiply(v)
        if reduce == "amax":
            return a.at[ix].max(v)
        if reduce == "amin":
            return a.at[ix].min(v)
        raise ValueError(f"unknown reduce {reduce}")
    if isinstance(values, (int, float)):
        return apply_op("put_along_axis", lambda a: f(a, values), arr)
    return apply_op("put_along_axis", f, arr, values)


def index_select(x, index, axis=0, name=None):
    idx = unwrap(index)
    return apply_op("index_select", lambda a: jnp.take(a, idx, axis=axis), x)


def index_add(x, index, axis, value, name=None):
    import builtins
    idx = unwrap(index)
    def f(a, v):
        # NB: builtins.slice — this module defines a paddle `slice` op
        sl = [builtins.slice(None)] * a.ndim
        sl[axis] = idx
        return a.at[tuple(sl)].add(v)
    return apply_op("index_add", f, x, value)


def index_put(x, indices, value, accumulate=False, name=None):
    ix = tuple(unwrap(i) for i in indices)
    def f(a, v):
        if accumulate:
            return a.at[ix].add(v)
        return a.at[ix].set(jnp.asarray(v, a.dtype))
    return apply_op("index_put", f, x, value)


def scatter(x, index, updates, overwrite=True, name=None):
    idx = unwrap(index)
    def f(a, u):
        if overwrite:
            return a.at[idx].set(u)
        base = a.at[idx].set(jnp.zeros_like(u))
        return base.at[idx].add(u)
    return apply_op("scatter", f, x, updates)


def scatter_nd_add(x, index, updates, name=None):
    idx = unwrap(index)
    def f(a, u):
        ix = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[ix].add(u)
    return apply_op("scatter_nd_add", f, x, updates)


def scatter_nd(index, updates, shape, name=None):
    idx = unwrap(index)
    sh = _shape_arg(shape)
    def f(u):
        a = jnp.zeros(sh, u.dtype)
        ix = tuple(jnp.moveaxis(idx, -1, 0))
        return a.at[ix].add(u)
    return apply_op("scatter_nd", f, updates)


def slice(input, axes, starts, ends, name=None):
    starts = [int(unwrap(s)) for s in starts]
    ends = [int(unwrap(e)) for e in ends]
    def f(a):
        sl = [slice_builtin(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            n = a.shape[ax]
            s2 = np.clip(s + n if s < 0 else s, 0, n)
            e2 = np.clip(e + n if e < 0 else e, 0, n)
            sl[ax] = slice_builtin(int(s2), int(e2))
        return a[tuple(sl)]
    return apply_op("slice", f, input)


slice_builtin = builtins.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    def f(a):
        sl = [slice_builtin(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = slice_builtin(int(unwrap(s)), int(unwrap(e)), int(unwrap(st)))
        return a[tuple(sl)]
    return apply_op("strided_slice", f, x)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = unwrap(repeats)
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.repeat(a, r, axis=0, total_repeat_length=None if np.ndim(r) == 0 else int(np.sum(np.asarray(r))))
        return jnp.repeat(a, r, axis=axis)
    return apply_op("repeat_interleave", f, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..nn.functional.common import pad as _pad
    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(a):
        size = index_num // nshards
        shard = a // size
        return jnp.where(shard == shard_id, a % size, ignore_value)
    return Tensor(f(unwrap(input)))


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    arr = np.asarray(unwrap(x))
    if axis is None:
        arr = arr.reshape(-1)
    keep = np.concatenate([[True], arr[1:] != arr[:-1]]) if arr.ndim == 1 else None
    out = arr[keep]
    res = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        res.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, arr.size))
        res.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return res[0] if len(res) == 1 else tuple(res)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None,
           dtype="int64", name=None):
    """Dynamic-shape op: eager only (host round-trip), like the reference's CPU sync."""
    arr = np.asarray(unwrap(x))
    out = np.unique(arr, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(out, tuple):
        return Tensor(jnp.asarray(out))
    res = [Tensor(jnp.asarray(o if i == 0 else o.astype(np.int64))) for i, o in enumerate(out)]
    return tuple(res)


def as_complex(x, name=None):
    return apply_op("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x, name=None):
    return apply_op("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), x)


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(axes, (list, tuple)):
        ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in axes)
    return apply_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


def atleast_1d(*inputs, name=None):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def unfold(x, axis, size, step, name=None):
    def f(a):
        n = a.shape[axis]
        num = (n - size) // step + 1
        starts = jnp.arange(num) * step
        idx = starts[:, None] + jnp.arange(size)[None, :]
        out = jnp.take(a, idx.reshape(-1), axis=axis)
        new_shape = list(a.shape)
        new_shape[axis:axis+1] = [num, size]
        out = out.reshape(new_shape)
        return jnp.moveaxis(out, axis + 1, -1)
    return apply_op("unfold", f, x)


def masked_fill(x, mask, value, name=None):
    m = unwrap(mask)
    if isinstance(value, (int, float)):
        return apply_op("masked_fill", lambda a: jnp.where(m, jnp.asarray(value, a.dtype), a), x)
    return apply_op("masked_fill", lambda a, v: jnp.where(m, v.astype(a.dtype), a), x, value)


def fill_diagonal(x, value, offset=0, wrap=False, name=None):
    def f(a):
        n = builtins.min(a.shape[-2], a.shape[-1])
        i = jnp.arange(n - builtins.abs(offset) if offset else n)
        if offset >= 0:
            return a.at[..., i, i + offset].set(value)
        return a.at[..., i - offset, i].set(value)
    return apply_op("fill_diagonal", f, x)
