"""Math ops closing the paddle.tensor surface gap (reference:
python/paddle/tensor/math.py — sinc, gammainc family, diff, trapezoid, vander,
renorm, isin, histogram family, reduce_as, block_diag; kernels under
phi/kernels/*)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from . import math as _math


def sinc(x, name=None):
    return apply_op("sinc", jnp.sinc, x)


def signbit(x, name=None):
    return apply_op("signbit", jnp.signbit, x)


gammaln = _math.lgamma


def gammainc(x, y, name=None):
    return apply_op("gammainc", jax.scipy.special.gammainc, x, y)


def gammaincc(x, y, name=None):
    return apply_op("gammaincc", jax.scipy.special.gammaincc, x, y)


def multigammaln(x, p, name=None):
    return apply_op("multigammaln",
                    lambda a: jax.scipy.special.multigammaln(a, p), x)


def polygamma(x, n, name=None):
    return apply_op("polygamma",
                    lambda a: jax.scipy.special.polygamma(n, a), x)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    args = [t for t in (prepend, append) if isinstance(t, Tensor)]

    def f(a, *rest):
        pre = rest[0] if isinstance(prepend, Tensor) else prepend
        app = rest[-1] if isinstance(append, Tensor) else append
        return jnp.diff(a, n=n, axis=axis, prepend=pre, append=app)

    return apply_op("diff", f, x, *args)


def sgn(x, name=None):
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)
    return apply_op("sgn", f, x)


def frexp(x, name=None):
    def f(a):
        m, e = jnp.frexp(a)
        return m, e.astype(a.dtype)
    return apply_op("frexp", f, x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply_op("trapezoid",
                        lambda a, b: jnp.trapezoid(a, x=b, axis=axis), y, x)
    return apply_op("trapezoid",
                    lambda a: jnp.trapezoid(a, dx=1.0 if dx is None else dx,
                                            axis=axis), y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def _cum(a, spacing):
        a0 = jnp.moveaxis(a, axis, -1)
        avg = (a0[..., 1:] + a0[..., :-1]) / 2.0
        out = jnp.cumsum(avg * spacing, axis=-1)
        return jnp.moveaxis(out, -1, axis)

    if x is not None:
        def f(a, b):
            b0 = jnp.moveaxis(b, axis, -1) if b.ndim == a.ndim else b
            d = jnp.diff(b0, axis=-1 if b.ndim == a.ndim else 0)
            if b.ndim != a.ndim:  # 1-D sample positions broadcast along axis
                shape = [1] * a.ndim
                shape[axis if axis >= 0 else a.ndim + axis] = -1
                d = d.reshape(shape)
                d = jnp.moveaxis(d, axis, -1)
            return _cum(a, d)
        return apply_op("cumulative_trapezoid", f, y, x)
    return apply_op("cumulative_trapezoid",
                    lambda a: _cum(a, 1.0 if dx is None else dx), y)


def vander(x, n=None, increasing=False, name=None):
    return apply_op("vander",
                    lambda a: jnp.vander(a, N=n, increasing=increasing), x)


def renorm(x, p, axis, max_norm, name=None):
    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.sum(jnp.abs(flat) ** p, axis=1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return apply_op("renorm", f, x)


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    return apply_op("isin",
                    lambda a, b: jnp.isin(a, b, assume_unique=assume_unique,
                                          invert=invert), x, test_x)


def histogram_bin_edges(input, bins=100, min=0, max=0, name=None):
    def f(a):
        rng = None if (min == 0 and max == 0) else (min, max)
        return jnp.histogram_bin_edges(a, bins=bins, range=rng)
    return apply_op("histogram_bin_edges", f, input)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    xs = unwrap(x)
    w = unwrap(weights) if isinstance(weights, Tensor) else weights
    hist, edges = jnp.histogramdd(xs, bins=bins, range=ranges,
                                  density=density, weights=w)
    return Tensor(hist), [Tensor(e) for e in edges]


def reduce_as(x, target, name=None):
    """Sum-reduce x to target's shape (reference reduce_as op)."""
    tshape = tuple(target.shape) if isinstance(target, Tensor) else tuple(target)

    def f(a):
        extra = a.ndim - len(tshape)
        if extra:
            a = jnp.sum(a, axis=tuple(range(extra)))
        keep = tuple(i for i, (s, t) in enumerate(zip(a.shape, tshape))
                     if s != t)
        if keep:
            a = jnp.sum(a, axis=keep, keepdims=True)
        return a
    return apply_op("reduce_as", f, x)


def block_diag(inputs, name=None):
    return apply_op("block_diag",
                    lambda *arrs: jax.scipy.linalg.block_diag(*arrs), *inputs)


def vecdot(x, y, axis=-1, name=None):
    """reference: paddle.linalg.vecdot (ops.yaml vecdot)."""
    def f(a, b):
        return jnp.sum(a * b, axis=axis)
    return apply_op("vecdot", f, x, y)


def combinations(x, r=2, with_replacement=False, name=None):
    """reference: paddle.combinations (itertools semantics over a 1-D
    tensor). Index set is static (host-side), the gather is device-side."""
    import itertools
    n = x.shape[0]
    idx = list(itertools.combinations_with_replacement(range(n), r)
               if with_replacement else itertools.combinations(range(n), r))
    if not idx:
        import numpy as _np
        return Tensor(jnp.zeros((0, r), unwrap(x).dtype))
    ix = jnp.asarray(idx)

    def f(a):
        return a[ix]
    return apply_op("combinations", f, x)


def pdist(x, p=2.0, name=None):
    """reference: paddle.pdist — condensed pairwise distances of [N, D]."""
    n = x.shape[0]
    iu = jnp.triu_indices(n, k=1)

    def f(a):
        # gather the i<j pairs FIRST: the full [n, n] matrix has sqrt(0) on
        # the diagonal whose vjp is inf -> 0*inf = NaN even though discarded
        d = jnp.abs(a[iu[0]] - a[iu[1]])       # [npairs, D]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(d * d, -1))
        if p == 0:
            return jnp.sum(d != 0, -1).astype(a.dtype)
        if p == float("inf"):
            return jnp.max(d, -1)
        return jnp.sum(d ** p, -1) ** (1.0 / p)
    return apply_op("pdist", f, x)
