"""Op surface assembly + Tensor method installation.

Reference analog: the monkey-patch of generated methods onto the eager Tensor type
(python/paddle/base/dygraph/math_op_patch.py + tensor/__init__.py method lists).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from . import (creation, math, manipulation, logic, linalg, search, random,
               stat, math_extra, manip_extra, linalg_extra)
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .math_extra import *  # noqa: F401,F403
from .manip_extra import *  # noqa: F401,F403
from .linalg_extra import *  # noqa: F401,F403
from .einsum_op import einsum  # noqa: F401


# ---- indexing ----------------------------------------------------------------
def _prep_index(item):
    """Convert an indexing object: unwrap Tensors, pass-through slices/ints/None."""
    if isinstance(item, tuple):
        return tuple(_prep_index(i) for i in item)
    if isinstance(item, Tensor):
        return unwrap(item)
    if isinstance(item, (list, np.ndarray)):
        return jnp.asarray(np.asarray(item))
    return item


def _getitem(self, item):
    idx = _prep_index(item)
    return apply_op("getitem", lambda a: a[idx], self)


def _setitem(self, item, value):
    idx = _prep_index(item)
    if isinstance(value, Tensor):
        out = apply_op("setitem", lambda a, v: a.at[idx].set(v.astype(a.dtype)), self, value)
    else:
        v = jnp.asarray(np.asarray(value)) if not np.isscalar(value) else value
        out = apply_op("setitem", lambda a: a.at[idx].set(v), self)
    self._data = out._data
    self._grad_node, self._out_slot = out._grad_node, out._out_slot
    if not out.stop_gradient:
        self.stop_gradient = False


def _iter(self):
    for i in range(len(self)):
        yield self[i]


# ---- astype ------------------------------------------------------------------
def _astype(self, dtype):
    return manipulation.cast(self, dtype)


# ---- operator overloads ------------------------------------------------------
def _coerce_scalar_op(name, fwd, rev=None):
    def f(self, other):
        o = other
        return apply_op(name, fwd, self, o) if isinstance(other, Tensor) else \
            apply_op(name, lambda a: fwd(a, _scalar(o, a)), self)
    def fr(self, other):
        o = other
        return apply_op(name, lambda a: (rev or (lambda x, y: fwd(y, x)))(a, _scalar(o, a)), self)
    return f, fr


def _scalar(o, a):
    if isinstance(o, (bool, int, float)):
        return o
    return jnp.asarray(np.asarray(o))


_add, _radd = _coerce_scalar_op("add", jnp.add)
_sub, _rsub = _coerce_scalar_op("subtract", jnp.subtract)
_mul, _rmul = _coerce_scalar_op("multiply", jnp.multiply)
_div, _rdiv = _coerce_scalar_op("divide", lambda a, b: jnp.true_divide(a, b))
_fdiv, _rfdiv = _coerce_scalar_op("floor_divide", jnp.floor_divide)
_mod, _rmod = _coerce_scalar_op("mod", jnp.mod)
_pow, _rpow = _coerce_scalar_op("pow", jnp.power)
_mat, _rmat = _coerce_scalar_op("matmul", jnp.matmul)


def _neg(self):
    return math.neg(self)


def _abs(self):
    return math.abs(self)


def _invert(self):
    return logic.bitwise_not(self) if self.dtype != np.dtype(bool) else logic.logical_not(self)


def _cmp_method(jfn):
    # through dispatch so capture and static replay record comparisons too
    def f(self, other):
        return apply_op(jfn.__name__, jfn, self, other)
    return f


def _inplace_from(fn):
    def f(self, *args, **kw):
        out = fn(self, *args, **kw)
        self._data = out._data
        self._grad_node, self._out_slot = out._grad_node, out._out_slot
        if not out.stop_gradient:
            self.stop_gradient = False
        return self
    return f


_METHODS = {
    # dunder
    "__getitem__": _getitem, "__setitem__": _setitem, "__iter__": _iter,
    "__add__": _add, "__radd__": _radd, "__sub__": _sub, "__rsub__": _rsub,
    "__mul__": _mul, "__rmul__": _rmul, "__truediv__": _div, "__rtruediv__": _rdiv,
    "__floordiv__": _fdiv, "__rfloordiv__": _rfdiv, "__mod__": _mod, "__rmod__": _rmod,
    "__pow__": _pow, "__rpow__": _rpow, "__matmul__": _mat, "__rmatmul__": _rmat,
    "__neg__": _neg, "__abs__": _abs, "__invert__": _invert,
    "__eq__": _cmp_method(jnp.equal), "__ne__": _cmp_method(jnp.not_equal),
    "__lt__": _cmp_method(jnp.less), "__le__": _cmp_method(jnp.less_equal),
    "__gt__": _cmp_method(jnp.greater), "__ge__": _cmp_method(jnp.greater_equal),
    "__and__": _cmp_method(jnp.logical_and), "__or__": _cmp_method(jnp.logical_or),
    "__xor__": _cmp_method(jnp.logical_xor),
    "astype": _astype, "cast": _astype,
}

# plain methods delegating to module-level ops (x.method(...) == ops.method(x, ...))
_DELEGATED = [
    # math
    "exp", "log", "log2", "log10", "log1p", "expm1", "sqrt", "rsqrt", "abs", "sign",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh", "asinh",
    "acosh", "atanh", "floor", "ceil", "round", "trunc", "frac", "square",
    "reciprocal", "neg", "erf", "erfinv", "lgamma", "digamma", "sigmoid", "logit",
    "conj", "angle", "real", "imag", "nan_to_num", "clip", "lerp", "isnan", "isinf",
    "isfinite", "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "fmod", "maximum", "minimum", "fmax", "fmin", "atan2", "pow",
    "scale", "sum", "mean", "prod", "max", "min", "amax", "amin", "logsumexp",
    "cumsum", "cumprod", "cummax", "cummin", "logcumsumexp", "nansum", "nanmean",
    "count_nonzero", "addmm", "outer", "kron", "trace", "diagonal", "dot", "matmul",
    "mm", "bmm", "mv", "inner",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than", "less_equal",
    "logical_and", "logical_or", "logical_xor", "logical_not", "bitwise_and",
    "bitwise_or", "bitwise_xor", "bitwise_not", "equal_all", "all", "any", "isclose",
    "allclose", "where",
    # manipulation
    "reshape", "reshape_", "transpose", "transpose_", "moveaxis", "swapaxes",
    "t", "squeeze",
    "unsqueeze", "split", "chunk", "unbind", "flatten", "tile", "expand",
    "broadcast_to", "expand_as", "flip", "rot90", "roll", "gather", "gather_nd",
    "take_along_axis", "put_along_axis", "index_select", "index_add", "index_put",
    "scatter", "scatter_nd_add", "repeat_interleave", "unfold", "masked_fill",
    "fill_diagonal", "unique", "unique_consecutive", "masked_select", "view",
    "tensordot", "as_complex", "as_real", "cast",
    # linalg
    "norm", "dist", "cross", "cholesky", "inverse", "pinv", "solve", "matrix_power",
    "det", "bincount", "histogram",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "kthvalue", "nonzero",
    "index_sample", "bucketize",
    # stat
    "var", "std", "median", "nanmedian", "quantile", "nanquantile",
    # creation
    "tril", "triu", "diag", "clone",
    # math_extra
    "sinc", "signbit", "gammaln", "gammainc", "gammaincc", "multigammaln",
    "polygamma", "diff", "sgn", "frexp", "trapezoid", "cumulative_trapezoid",
    "vander", "renorm", "isin", "histogram_bin_edges", "reduce_as",
    "vecdot", "combinations", "pdist",
    # manip_extra
    "reverse", "less", "bitwise_invert", "tensor_split", "hsplit", "vsplit",
    "dsplit", "unstack", "take", "unflatten", "as_strided", "view_as",
    "matrix_transpose", "rank", "is_complex", "is_integer", "is_floating_point",
    "slice_scatter", "select_scatter", "diagonal_scatter", "index_fill",
    "masked_scatter",
    # linalg_extra
    "lu", "lu_unpack", "ormqr", "cond", "cholesky_inverse", "cdist",
    # random extras
    "top_p_sampling", "cauchy_", "geometric_", "log_normal_", "uniform_",
    "normal_", "exponential_",
]

_INPLACE = {
    "add_": math.add, "subtract_": math.subtract, "multiply_": math.multiply,
    "divide_": math.divide, "scale_": math.scale, "clip_": math.clip,
    "floor_": math.floor, "ceil_": math.ceil, "round_": math.round,
    "exp_": math.exp, "sqrt_": math.sqrt, "rsqrt_": math.rsqrt,
    "reciprocal_": math.reciprocal, "tanh_": math.tanh, "sigmoid_": math.sigmoid,
    "abs_": math.abs, "neg_": math.neg, "pow_": math.pow, "remainder_": math.mod,
    "lerp_": math.lerp, "squeeze_": manipulation.squeeze,
    "unsqueeze_": manipulation.unsqueeze, "flatten_": manipulation.flatten,
    "masked_fill_": manipulation.masked_fill, "index_put_": manipulation.index_put,
    "fill_diagonal_": manipulation.fill_diagonal, "cast_": manipulation.cast,
    "scatter_": manipulation.scatter, "where_": logic.where,
}

# the remaining in-place tensor_method_func surface is mechanical: `name_`
# computes out-of-place then rebinds the buffer (reference inplace codegen,
# paddle/fluid/pybind/eager_generator: *_ apis)
_AUTO_INPLACE = [
    "asin", "cumsum", "cumprod", "logit", "log", "log2", "log10", "square",
    "multigammaln", "nan_to_num", "hypot", "floor_divide", "mod", "log1p",
    "addmm", "lgamma", "gammaincc", "gammainc", "equal", "greater_equal",
    "greater_than", "less_equal", "less_than", "less", "logical_and",
    "logical_not", "logical_or", "logical_xor", "not_equal", "tan", "gammaln",
    "digamma", "trunc", "frac", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not", "bitwise_invert", "atanh", "gcd", "lcm", "erfinv",
    "put_along_axis", "ldexp", "i0", "polygamma", "renorm", "tril", "triu",
    "acos", "atan", "cos", "cosh", "sin", "sinc", "sinh", "acosh", "asinh",
    "copysign", "bitwise_left_shift", "bitwise_right_shift", "index_fill",
    "masked_scatter", "t", "erf", "expm1",
]


def _set_(self, source, name=None):
    """x.set_(y): rebind x's buffer/shape/dtype to y's (reference set_ op)."""
    src = source._data if isinstance(source, Tensor) else jnp.asarray(source)
    self._data = src
    return self


def _install():
    import sys
    mod = sys.modules[__name__]
    for name, fn in _METHODS.items():
        setattr(Tensor, name, fn)
    for name in _DELEGATED:
        fn = getattr(mod, name, None)
        if fn is None:
            continue
        def make(f):
            def m(self, *a, **k):
                return f(self, *a, **k)
            return m
        setattr(Tensor, name, make(fn))
    for name, fn in _INPLACE.items():
        setattr(Tensor, name, _inplace_from(fn))
        setattr(mod, name, _inplace_from(fn))   # paddle.abs_(t) module form
    for base in _AUTO_INPLACE:
        fn = getattr(mod, base, None)
        if fn is not None:
            setattr(Tensor, base + "_", _inplace_from(fn))
            setattr(mod, base + "_", _inplace_from(fn))
    # paddle name quirk: floor_mod_ aliases mod_
    Tensor.floor_mod_ = Tensor.mod_
    mod.floor_mod_ = mod.remainder_
    Tensor.set_ = _set_
    # random inplace
    from .random import (uniform_, normal_, exponential_, bernoulli_,
                         cauchy_, geometric_, log_normal_)
    Tensor.uniform_ = uniform_
    Tensor.normal_ = normal_
    Tensor.exponential_ = exponential_
    Tensor.bernoulli_ = bernoulli_
    Tensor.cauchy_ = cauchy_
    Tensor.geometric_ = geometric_
    Tensor.log_normal_ = log_normal_

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self
    Tensor.fill_ = fill_

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self
    Tensor.zero_ = zero_

    def set_value(self, value):
        v = value._data if isinstance(value, Tensor) else jnp.asarray(np.asarray(value))
        self._data = v.astype(self._data.dtype).reshape(self._data.shape)
        return self
    Tensor.set_value = set_value

    def _stft_m(self, *a, **k):
        from ..signal import stft
        return stft(self, *a, **k)

    def _istft_m(self, *a, **k):
        from ..signal import istft
        return istft(self, *a, **k)
    Tensor.stft = _stft_m
    Tensor.istft = _istft_m


_install()
