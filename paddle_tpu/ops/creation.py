"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..core.dispatch import apply_op, unwrap
from ..core.device import _parse


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(unwrap(s)) if not isinstance(s, (int, np.integer)) else int(s) for s in shape)


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    if d is None:
        d = default if default is not None else dtypes.get_default_dtype()
    return d


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        t = data.astype(dtype) if dtype is not None and np.dtype(data.dtype) != dtypes.convert_dtype(dtype) else Tensor(data._data)
        t.stop_gradient = stop_gradient
        return t
    if isinstance(data, (jnp.ndarray, jax.Array)) or isinstance(data, jax.core.Tracer):
        arr = data if dtype is None else data.astype(dtypes.convert_dtype(dtype))
    else:
        npd = np.asarray(data)
        if dtype is None and npd.dtype == np.float64:
            npd = npd.astype(dtypes.get_default_dtype())  # paddle default-dtype convention
        elif dtype is not None:
            npd = npd.astype(dtypes.convert_dtype(dtype))
        dev = _parse(place) if place is not None else None
        arr = jax.device_put(npd, dev)
    return Tensor(arr, stop_gradient=stop_gradient)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_norm_shape(shape), dtype=_dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_norm_shape(shape), dtype=_dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fill = unwrap(fill_value)
    if dtype is None and isinstance(fill_value, (bool, int, float)):
        if isinstance(fill_value, bool):
            dtype = dtypes.bool_
        elif isinstance(fill_value, int):
            dtype = dtypes.int64
        else:
            dtype = dtypes.get_default_dtype()
    return Tensor(jnp.full(_norm_shape(shape), fill, dtype=_dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype, name)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(unwrap(x), dtype=dtypes.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(unwrap(x), dtype=dtypes.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(unwrap(x), unwrap(fill_value), dtype=dtypes.convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype, name)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start, end, step = unwrap(start), unwrap(end), unwrap(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        py = all(isinstance(v, (int, np.integer)) for v in (start, end, step))
        dtype = dtypes.int64 if py else dtypes.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                               base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1 and padding_value != 0:
            d = jnp.diag(a, k=offset)
            mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
            return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
        return jnp.diag(a, k=offset)
    return apply_op("diag", f, x)


def diagflat(x, offset=0, name=None):
    return apply_op("diagflat", lambda a: jnp.diagflat(a, k=offset), x)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        out = jnp.zeros(a.shape + (a.shape[-1] + abs(offset),), a.dtype)
        idx = jnp.arange(a.shape[-1])
        if offset >= 0:
            out = out.at[..., idx, idx + offset].set(a)
        else:
            out = out.at[..., idx - offset, idx].set(a)
        nd = out.ndim
        d1, d2 = dim1 % nd, dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # move last two dims to (dim1, dim2)
        order = list(range(nd - 2))
        order.insert(min(d1, d2), nd - 2)
        order.insert(max(d1, d2), nd - 1)
        return jnp.transpose(out, order)
    return apply_op("diag_embed", f, x)


def tril(x, diagonal=0, name=None):
    return apply_op("tril", lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply_op("triu", lambda a: jnp.triu(a, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=dtypes.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=dtypes.convert_dtype(dtype)))


def meshgrid(*args, **kwargs):
    arrs = [unwrap(a) for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return [Tensor(g) for g in jnp.meshgrid(*arrs, indexing="ij")]


def clone(x, name=None):
    return apply_op("clone", lambda a: a + jnp.zeros((), a.dtype) if a.dtype != jnp.bool_ else a.copy(), x)


def assign(x, output=None):
    src = unwrap(x) if isinstance(x, Tensor) else jnp.asarray(np.asarray(x))
    if output is None:
        return Tensor(src)
    output._data = jnp.asarray(src, output._data.dtype) if hasattr(output._data, "dtype") else src
    return output


def complex(real, imag, name=None):
    return apply_op("complex", lambda r, i: jax.lax.complex(r, i), real, imag)


def polar(abs, angle, name=None):
    return apply_op("polar", lambda r, t: jax.lax.complex(r * jnp.cos(t), r * jnp.sin(t)), abs, angle)


def create_tensor(dtype, name=None, persistable=False):
    """reference python/paddle/tensor/creation.py create_tensor."""
    return Tensor(jnp.zeros((), _dt(dtype)))


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference python/paddle/tensor/creation.py create_parameter."""
    from ..core.tensor import Parameter
    dt = _dt(dtype)
    if default_initializer is not None:
        t = Tensor(jnp.zeros(_norm_shape(shape), dt))
        default_initializer(t)
        arr = t._data
    elif is_bias:
        arr = jnp.zeros(_norm_shape(shape), dt)
    else:
        k = float(np.sqrt(6.0 / max(1, int(np.prod(shape)))))
        from ..core.rng import next_key
        import jax as _jax
        arr = _jax.random.uniform(next_key(), _norm_shape(shape), jnp.float32,
                                  -k, k).astype(dt)
    p = Parameter(arr)
    p.stop_gradient = False
    return p
