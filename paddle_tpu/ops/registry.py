"""Declarative op registry — the ops.yaml analog (SURVEY §7 stage 1;
reference phi/ops/yaml/ops.yaml + python/paddle/tensor/__init__.py
tensor_method_func).

One table drives everything:
  * name, category, resolver         — the public API entry
  * np_ref                           — numpy golden for OpTest check_output
  * sample                           — input builder (seeded, deterministic)
  * grad                             — finite-difference grad-check eligible
  * kind                             — "golden" | "smoke" | "alias" | "inplace"

tests/test_op_suite.py parametrizes over the registry; coverage_report()
measures surface parity against the reference's tensor_method_func list.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


# ---- sample builders ---------------------------------------------------------
def _rng(seed=0):
    return np.random.RandomState(seed)


def uniform_builder(default_lo, default_hi):
    """The one seeded sample-builder factory: every float range builder
    (U/POS/UNIT/GT1/PROB) is an instance, so they all share one canonical
    signature ``(*shape, lo=..., hi=..., dtype=np.float32, seed=0)`` for the
    registry-parity pass to check."""
    def build(*shape, lo=default_lo, hi=default_hi, dtype=np.float32, seed=0):
        return _rng(seed).uniform(lo, hi, shape).astype(dtype)
    return build


U = uniform_builder(-2.0, 2.0)       # generic signed values
POS = uniform_builder(0.1, 3.0)      # strictly positive (log/sqrt domains)
UNIT = uniform_builder(-0.9, 0.9)    # open unit interval (atanh/asin domains)
GT1 = uniform_builder(1.1, 3.0)      # > 1 (acosh domain)
PROB = uniform_builder(0.05, 0.95)   # probabilities bounded away from 0/1


def I(*shape, lo=0, hi=5, seed=0):
    return _rng(seed).randint(lo, hi, shape).astype(np.int32)


def B(*shape, seed=0):
    return _rng(seed).rand(*shape) > 0.5


def SPD(n=4, seed=0):
    a = U(n, n, seed=seed)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


@dataclass
class OpSpec:
    name: str
    category: str
    op: object = None            # None -> resolve getattr(paddle_tpu.ops, name)
    np_ref: object = None
    sample: object = None        # () -> list of input arrays
    kwargs: dict = field(default_factory=dict)
    grad: bool = False
    grad_idx: tuple = None
    atol: float = 1e-5
    rtol: float = 1e-5
    kind: str = "golden"         # golden | smoke | alias | inplace
    alias_of: str = None
    check: object = None         # golden-by-property: (raw, out) -> asserts
    reason: str = None           # kind="smoke": why no numeric golden exists

    def resolve(self):
        if callable(self.op):
            return self.op
        import paddle_tpu.ops as O
        target = self.op or self.name
        if "." in target:
            import importlib
            modname, attr = target.rsplit(".", 1)
            return getattr(importlib.import_module(modname), attr)
        return getattr(O, target)


REGISTRY: dict[str, OpSpec] = {}

# the category vocabulary; registry-parity rejects entries outside it
CATEGORIES = frozenset({
    "math", "reduce", "linalg", "logic", "manip", "search", "stat",
    "creation", "random", "fft", "signal", "inplace"})

# names registered more than once (the later entry shadows the earlier);
# recorded instead of raising so the registry-parity pass can report every
# collision with a location rather than dying on the first
DUPLICATE_REGISTRATIONS: list[str] = []


def register(spec: OpSpec):
    if spec.name in REGISTRY:
        DUPLICATE_REGISTRATIONS.append(spec.name)
    REGISTRY[spec.name] = spec
    return spec


def u(name, ref, sample=None, grad=True, cat="math", **kw):
    """Unary elementwise golden entry."""
    return register(OpSpec(name, cat, np_ref=ref,
                           sample=sample or (lambda: [U(3, 4)]),
                           grad=grad, **kw))


def b(name, ref, sample=None, grad=True, cat="math", **kw):
    """Binary elementwise golden entry."""
    return register(OpSpec(name, cat, np_ref=ref,
                           sample=sample or (lambda: [U(3, 4), U(3, 4, seed=1)]),
                           grad=grad, **kw))


def g(name, ref, sample, cat, grad=False, **kw):
    """General golden entry."""
    return register(OpSpec(name, cat, np_ref=ref, sample=sample, grad=grad, **kw))


def smoke(name, sample, cat, op=None, reason=None, **kw):
    """Runs the op on sample inputs; checks finiteness/shape only. Every
    smoke entry must carry a one-line `reason` (VERDICT r4 weak #4: the
    numerically verified surface is what counts; execute-only entries need a
    documented excuse — e.g. RNG-valued output)."""
    if not reason:
        # ValueError, not assert: the rule must survive `python -O`
        raise ValueError(f"smoke op {name!r} needs a documented reason")
    return register(OpSpec(name, cat, op=op, sample=sample, kind="smoke",
                           reason=reason, **kw))


def alias(name, of, cat):
    return register(OpSpec(name, cat, kind="alias", alias_of=of))


def inplace(name, of, cat="inplace"):
    return register(OpSpec(name, cat, kind="inplace", alias_of=of))


# ---- golden-by-property checks ----------------------------------------------
# Decompositions have sign/order/phase ambiguity, so elementwise goldens are
# ill-posed; these assert reconstruction + structural invariants instead
# (the same bar OpTest applies to its decomposition ops).
def _tonp(o):
    return np.asarray(o.numpy() if hasattr(o, "numpy") else o)


def _chk_qr(raw, out):
    (a,) = raw
    q, r = _tonp(out[0]), _tonp(out[1])
    np.testing.assert_allclose(q @ r, a, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(q.T @ q, np.eye(q.shape[1]), atol=1e-4)
    assert np.allclose(r, np.triu(r), atol=1e-6)


def _chk_svd(raw, out):
    (a,) = raw
    u, s, v = _tonp(out[0]), _tonp(out[1]), _tonp(out[2])   # paddle svd returns V
    np.testing.assert_allclose((u * s) @ v.T, a, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(
        s, np.linalg.svd(a, compute_uv=False), atol=1e-4, rtol=1e-4)
    assert (np.diff(s) <= 1e-6).all()                    # descending


def _chk_eig(raw, out):
    (a,) = raw
    w, v = _tonp(out[0]).astype(np.complex128), _tonp(out[1]).astype(np.complex128)
    np.testing.assert_allclose(a.astype(np.complex128) @ v, v * w[None, :],
                               atol=1e-3, rtol=1e-3)
    ref = np.sort_complex(np.linalg.eigvals(a.astype(np.float64)))
    np.testing.assert_allclose(np.sort_complex(w), ref, atol=1e-3, rtol=1e-3)


def _chk_eigvals(raw, out):
    (a,) = raw
    w = _tonp(out).astype(np.complex128)
    ref = np.sort_complex(np.linalg.eigvals(a.astype(np.float64)))
    np.testing.assert_allclose(np.sort_complex(w), ref, atol=1e-3, rtol=1e-3)


def _chk_eigh(raw, out):
    (a,) = raw
    w, v = _tonp(out[0]), _tonp(out[1])
    np.testing.assert_allclose((v * w) @ v.T, a, atol=1e-3, rtol=1e-3)
    np.testing.assert_allclose(w, np.linalg.eigvalsh(a), atol=1e-3, rtol=1e-3)


def _chk_lu(raw, out):
    (a,) = raw
    lu_packed, piv = _tonp(out[0]), _tonp(out[1])
    l = np.tril(lu_packed, -1) + np.eye(a.shape[0])
    u = np.triu(lu_packed)
    perm = np.arange(a.shape[0])
    for i, p in enumerate(piv):                    # pivots -> permutation
        perm[[i, int(p) - 1]] = perm[[int(p) - 1, i]]
    np.testing.assert_allclose((l @ u), a[perm], atol=1e-4, rtol=1e-4)


def _chk_lu_unpack(raw, out):
    (a,) = raw
    p, l, u = _tonp(out[0]), _tonp(out[1]), _tonp(out[2])
    np.testing.assert_allclose(p @ l @ u, a, atol=1e-4, rtol=1e-4)
    assert np.allclose(l, np.tril(l)) and np.allclose(u, np.triu(u))


def _householder_q(a, tau):
    """numpy reconstruction of the Householder product (geqrf convention)."""
    m, k = a.shape[0], len(tau)
    q = np.eye(m)
    for i in range(k):
        v = np.zeros((m,))
        v[i] = 1.0
        v[i + 1:] = a[i + 1:, i]
        q = q @ (np.eye(m) - tau[i] * np.outer(v, v))
    return q


def _chk_householder_product(raw, out):
    a, tau = raw
    np.testing.assert_allclose(_tonp(out), _householder_q(a, tau)[:, :a.shape[1]],
                               atol=1e-4, rtol=1e-4)


def _chk_ormqr(raw, out):
    a, tau, c = raw
    np.testing.assert_allclose(_tonp(out), _householder_q(a, tau) @ c,
                               atol=1e-4, rtol=1e-4)


def _chk_lstsq(raw, out):
    a, b_ = raw
    sol_ref, _, _, sv_ref = np.linalg.lstsq(a, b_, rcond=None)
    np.testing.assert_allclose(_tonp(out[0]), sol_ref, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(_tonp(out[3]), sv_ref, atol=1e-4, rtol=1e-4)


def _chk_istft(raw, out):
    # exact inverse property: istft(stft(x), length=n) == x
    (x,) = raw
    np.testing.assert_allclose(_tonp(out), x, atol=1e-4, rtol=1e-4)


def _chk_unique(raw, out):
    (x,) = raw
    np.testing.assert_array_equal(_tonp(out), np.unique(x))





# =============================================================================
# math: unary elementwise
# =============================================================================
u("exp", np.exp)
u("expm1", np.expm1)
u("log", np.log, lambda: [POS(3, 4)])
u("log2", np.log2, lambda: [POS(3, 4)])
u("log10", np.log10, lambda: [POS(3, 4)])
u("log1p", np.log1p, lambda: [POS(3, 4)])
u("sqrt", np.sqrt, lambda: [POS(3, 4)])
u("rsqrt", lambda x: 1 / np.sqrt(x), lambda: [POS(3, 4)])
u("abs", np.abs)
u("sign", np.sign, grad=False)
u("sgn", np.sign, grad=False)
u("sin", np.sin)
u("cos", np.cos)
u("tan", np.tan, lambda: [UNIT(3, 4)])
u("asin", np.arcsin, lambda: [UNIT(3, 4)])
u("acos", np.arccos, lambda: [UNIT(3, 4)])
u("atan", np.arctan)
u("sinh", np.sinh)
u("cosh", np.cosh)
u("tanh", np.tanh)
u("asinh", np.arcsinh)
u("acosh", np.arccosh, lambda: [GT1(3, 4)])
u("atanh", np.arctanh, lambda: [UNIT(3, 4)])
u("floor", np.floor, grad=False)
u("ceil", np.ceil, grad=False)
u("round", np.round, grad=False)
u("trunc", np.trunc, grad=False)
u("frac", lambda x: x - np.trunc(x))
u("square", np.square)
u("reciprocal", lambda x: 1.0 / x, lambda: [POS(3, 4)])
u("neg", np.negative)
u("sigmoid", lambda x: 1 / (1 + np.exp(-x)))
u("sinc", np.sinc, grad=False)
u("signbit", np.signbit, grad=False)
u("deg2rad", np.deg2rad)
u("rad2deg", np.rad2deg)
u("isnan", np.isnan, grad=False)
u("isinf", np.isinf, grad=False)
u("isfinite", np.isfinite, grad=False)
u("isreal", np.isreal, grad=False)
u("isneginf", np.isneginf, grad=False)
u("isposinf", np.isposinf, grad=False)


def _scipy(name):
    import scipy.special as ss
    return getattr(ss, name)


u("erf", lambda x: _scipy("erf")(x))
u("erfinv", lambda x: _scipy("erfinv")(x), lambda: [UNIT(3, 4)])
u("lgamma", lambda x: _scipy("gammaln")(x), lambda: [POS(3, 4)])
u("gammaln", lambda x: _scipy("gammaln")(x), lambda: [POS(3, 4)])
u("digamma", lambda x: _scipy("psi")(x), lambda: [POS(3, 4)], atol=1e-4)
u("i0", lambda x: _scipy("i0")(x), grad=False)
u("i0e", lambda x: _scipy("i0e")(x), grad=False)
u("i1", lambda x: _scipy("i1")(x), grad=False)
u("i1e", lambda x: _scipy("i1e")(x), grad=False)
u("logit", lambda x: np.log(x / (1 - x)), lambda: [PROB(3, 4)])
g("polygamma", lambda x: _scipy("polygamma")(1, x), lambda: [POS(3, 4)],
  "math", kwargs={"n": 1}, atol=1e-3, rtol=1e-3)
g("multigammaln", lambda x: _scipy("multigammaln")(x, 2),
  lambda: [GT1(3, 4)], "math", kwargs={"p": 2}, atol=1e-4)
b("gammainc", lambda a, x: _scipy("gammainc")(a, x),
  lambda: [POS(3, 4), POS(3, 4, seed=1)], grad=False)
b("gammaincc", lambda a, x: _scipy("gammaincc")(a, x),
  lambda: [POS(3, 4), POS(3, 4, seed=1)], grad=False)

# ---- binary elementwise ------------------------------------------------------
b("add", np.add)
b("subtract", np.subtract)
b("multiply", np.multiply)
b("divide", lambda a, b_: a / b_, lambda: [U(3, 4), POS(3, 4, seed=1)])
b("floor_divide", lambda a, b_: np.floor_divide(a, b_),
  lambda: [U(3, 4), POS(3, 4, seed=1)], grad=False)
b("mod", np.mod, lambda: [U(3, 4), POS(3, 4, seed=1)], grad=False)
alias("floor_mod", "mod", "math")
alias("remainder", "mod", "math")
b("fmod", np.fmod, lambda: [U(3, 4), POS(3, 4, seed=1)], grad=False)
b("maximum", np.maximum)
b("minimum", np.minimum)
b("fmax", np.fmax)
b("fmin", np.fmin)
b("atan2", np.arctan2)
b("pow", np.power, lambda: [POS(3, 4), U(3, 4, lo=0.5, hi=2, seed=1)])
b("hypot", np.hypot)
b("copysign", np.copysign, grad=False)
b("nextafter", np.nextafter, grad=False)
b("heaviside", np.heaviside, grad=False)
b("logaddexp", np.logaddexp)
b("ldexp", lambda a, b_: np.ldexp(a, b_),
  lambda: [U(3, 4), I(3, 4, lo=-3, hi=3, seed=1)], grad=False)
b("gcd", np.gcd, lambda: [I(3, 4, lo=1, hi=20), I(3, 4, lo=1, hi=20, seed=1)],
  grad=False)
b("lcm", np.lcm, lambda: [I(3, 4, lo=1, hi=10), I(3, 4, lo=1, hi=10, seed=1)],
  grad=False)
g("lerp", lambda x, y, w: x + w * (y - x),
  lambda: [U(3, 4), U(3, 4, seed=1), PROB(3, 4, seed=2)], "math", grad=True)
g("scale", lambda x: 2.5 * x + 1.0, lambda: [U(3, 4)], "math",
  kwargs={"scale": 2.5, "bias": 1.0}, grad=True)
g("clip", lambda x: np.clip(x, -1, 1), lambda: [U(3, 4)], "math",
  kwargs={"min": -1.0, "max": 1.0}, grad=True)
g("nan_to_num", np.nan_to_num, lambda: [U(3, 4)], "math", grad=False)
g("stanh", lambda x: 1.7159 * np.tanh(0.67 * x), lambda: [U(3, 4)],
  "math", grad=True, atol=1e-4)
g("increment", lambda x: x + 1.0, lambda: [U(3,)], "math", grad=False)
g("angle", np.angle, lambda: [U(3, 4)], "math", grad=False)
g("conj", np.conj, lambda: [U(3, 4)], "math", grad=False)
g("real", np.real, lambda: [U(3, 4)], "math", grad=False)
g("imag", np.imag, lambda: [U(3, 4)], "math", grad=False)

# ---- reductions --------------------------------------------------------------
g("sum", np.sum, lambda: [U(3, 4)], "reduce", grad=True)
g("mean", np.mean, lambda: [U(3, 4)], "reduce", grad=True)
g("prod", np.prod, lambda: [PROB(2, 3)], "reduce", grad=True)
g("max", np.max, lambda: [U(3, 4)], "reduce")
g("min", np.min, lambda: [U(3, 4)], "reduce")
g("amax", np.max, lambda: [U(3, 4)], "reduce")
g("amin", np.min, lambda: [U(3, 4)], "reduce")
g("logsumexp", lambda x: _scipy("logsumexp")(x), lambda: [U(3, 4)], "reduce",
  grad=True)
g("count_nonzero", np.count_nonzero, lambda: [I(3, 4)], "reduce")
g("nansum", np.nansum, lambda: [U(3, 4)], "reduce")
g("nanmean", np.nanmean, lambda: [U(3, 4)], "reduce")
g("all", np.all, lambda: [B(3, 4)], "reduce")
g("any", np.any, lambda: [B(3, 4)], "reduce")
g("cumsum", lambda x: np.cumsum(x), lambda: [U(3, 4)], "reduce", grad=True)
g("cumprod", lambda x: np.cumprod(x.reshape(-1)), lambda: [PROB(6)],
  "reduce", kwargs={"dim": 0}, grad=True)
g("cummax", lambda x: (np.maximum.accumulate(x.reshape(-1)),
                       np.array([int(np.argmax(x.reshape(-1)[:i + 1]))
                                 for i in range(x.size)])),
  lambda: [U(6)], "reduce")
g("cummin", lambda x: (np.minimum.accumulate(x.reshape(-1)),
                       np.array([int(np.argmin(x.reshape(-1)[:i + 1]))
                                 for i in range(x.size)])),
  lambda: [U(6)], "reduce")
g("logcumsumexp", lambda x: np.log(np.cumsum(np.exp(x))), lambda: [U(6)],
  "reduce", grad=True, atol=1e-4)
g("diff", lambda x: np.diff(x), lambda: [U(3, 6)], "math", grad=True)
g("trapezoid", lambda y: np.trapezoid(y), lambda: [U(3, 6)], "math", grad=True)
g("cumulative_trapezoid",
  lambda y: np.stack([np.cumsum((r[1:] + r[:-1]) / 2) for r in y]),
  lambda: [U(3, 6)], "math", grad=True)
g("vander", lambda x: np.vander(x), lambda: [U(4)], "math", grad=False)
def _renorm_ref(x):
    out = np.moveaxis(np.asarray(x), 1, 0).copy()
    for i in range(out.shape[0]):
        nrm = np.sqrt((out[i] ** 2).sum())
        if nrm > 1.0:
            out[i] *= 1.0 / nrm
    return np.moveaxis(out, 0, 1)


g("renorm", _renorm_ref, lambda: [U(3, 4, 5)], "math",
  kwargs={"p": 2.0, "axis": 1, "max_norm": 1.0}, atol=1e-4, rtol=1e-4)
g("isin", np.isin, lambda: [I(3, 4), I(5, seed=1)], "math")
g("histogram_bin_edges", lambda x: np.histogram_bin_edges(x, 10),
  lambda: [U(20)], "math", kwargs={"bins": 10})
g("reduce_as", lambda x: x.sum(0), lambda: [U(3, 4)], "math",
  kwargs={"target": np.zeros((4,), np.float32)})
g("frexp", lambda x: (np.frexp(x)[0], np.frexp(x)[1].astype(np.float32)),
  lambda: [POS(3, 4)], "math")
g("vecdot", lambda x, y: np.sum(x * y, -1),
  lambda: [U(3, 4), U(3, 4, seed=1)], "math", grad=True)
g("combinations",
  lambda x: np.array(list(__import__("itertools").combinations(x, 2))),
  lambda: [U(5)], "math")
g("pdist",
  lambda x: __import__("scipy.spatial.distance",
                       fromlist=["pdist"]).pdist(x),
  lambda: [U(5, 3)], "math", grad=True)
g("block_diag",
  lambda xs: __import__("scipy.linalg", fromlist=["block_diag"]).block_diag(
      *xs),
  lambda: [[U(2, 2), U(3, 3, seed=1)]], "math")

# ---- matmul family -----------------------------------------------------------
g("matmul", np.matmul, lambda: [U(3, 4), U(4, 5, seed=1)], "linalg", grad=True)
g("mm", np.matmul, lambda: [U(3, 4), U(4, 5, seed=1)], "linalg", grad=True)
g("bmm", np.matmul, lambda: [U(2, 3, 4), U(2, 4, 5, seed=1)], "linalg",
  grad=True)
g("dot", lambda a, b_: np.dot(a, b_), lambda: [U(5), U(5, seed=1)], "linalg",
  grad=True)
g("mv", lambda a, b_: a @ b_, lambda: [U(3, 4), U(4, seed=1)], "linalg",
  grad=True)
g("inner", np.inner, lambda: [U(3, 4), U(5, 4, seed=1)], "linalg", grad=True)
g("outer", np.outer, lambda: [U(3), U(4, seed=1)], "linalg", grad=True)
g("kron", np.kron, lambda: [U(2, 3), U(3, 2, seed=1)], "linalg", grad=True)
g("addmm", lambda c, a, b_: c + a @ b_,
  lambda: [U(3, 5), U(3, 4, seed=1), U(4, 5, seed=2)], "linalg", grad=True)
g("trace", np.trace, lambda: [U(4, 4)], "linalg", grad=True)
g("diagonal", lambda x: np.diagonal(x), lambda: [U(4, 5)], "linalg")
g("dist", lambda x, y: np.linalg.norm(x - y), lambda: [U(3, 4), U(3, 4, seed=1)],
  "linalg", grad=True)
g("multi_dot", lambda xs: xs[0] @ xs[1] @ xs[2],
  lambda: [[U(3, 4), U(4, 5, seed=1), U(5, 2, seed=2)]],
  "linalg", atol=1e-4, rtol=1e-4)
g("einsum", lambda a, b_: np.einsum("ij,jk->ik", a, b_),
  lambda: [U(3, 4), U(4, 5, seed=1)], "linalg", atol=1e-4, rtol=1e-4,
  op=lambda a, b_: __import__("paddle_tpu.ops", fromlist=["einsum"]).einsum(
      "ij,jk->ik", a, b_))

# ---- linalg decompositions ---------------------------------------------------
g("norm", lambda x: np.linalg.norm(x), lambda: [U(3, 4)], "linalg", grad=True)
g("vector_norm", lambda x: np.linalg.norm(x.reshape(-1)), lambda: [U(3, 4)],
  "linalg")
g("matrix_norm", lambda x: np.linalg.norm(x, "fro", axis=(-2, -1)),
  lambda: [U(3, 4)], "linalg")
g("cholesky", np.linalg.cholesky, lambda: [SPD(4)], "linalg", grad=True,
  atol=1e-4, rtol=1e-4)
g("cholesky_solve",
  lambda b_, y: np.linalg.solve(np.tril(y) @ np.tril(y).T, b_),
  lambda: [U(4, 2), SPD(4)], "linalg", atol=1e-3, rtol=1e-3)
g("cholesky_inverse", lambda l: np.linalg.inv(l @ l.T),
  lambda: [np.linalg.cholesky(SPD(4)).astype(np.float32)], "linalg",
  atol=1e-3, rtol=1e-3)
g("inverse", np.linalg.inv, lambda: [SPD(4)], "linalg", grad=True,
  atol=1e-4, rtol=1e-4)
alias("inv", "inverse", "linalg")
g("pinv", np.linalg.pinv, lambda: [U(4, 3)], "linalg", atol=1e-4, rtol=1e-4)
g("solve", np.linalg.solve, lambda: [SPD(4), U(4, 2, seed=1)], "linalg",
  grad=True, atol=1e-4, rtol=1e-4)
g("triangular_solve",
  lambda a, b_: np.linalg.solve(np.triu(a), b_),
  lambda: [np.triu(SPD(4)).astype(np.float32), U(4, 2, seed=1)], "linalg",
  atol=1e-4, rtol=1e-4)
g("lstsq", None, lambda: [U(5, 3), U(5, 2, seed=1)], "linalg",
  check=_chk_lstsq)
g("qr", None, lambda: [U(4, 3)], "linalg", check=_chk_qr)
g("svd", None, lambda: [U(4, 3)], "linalg", check=_chk_svd,
  kwargs={"full_matrices": False})
g("svdvals", lambda x: np.linalg.svd(x, compute_uv=False), lambda: [U(4, 3)],
  "linalg", atol=1e-4, rtol=1e-4)
g("eig", None, lambda: [U(4, 4)], "linalg", check=_chk_eig)
g("eigh", None, lambda: [SPD(4)], "linalg", check=_chk_eigh)
g("eigvals", None, lambda: [U(4, 4)], "linalg", check=_chk_eigvals)
g("eigvalsh", lambda x: np.linalg.eigvalsh(x), lambda: [SPD(4)], "linalg",
  atol=1e-3, rtol=1e-3)
g("matrix_rank", lambda x: np.linalg.matrix_rank(x), lambda: [U(4, 4)],
  "linalg")
g("matrix_power", lambda x: np.linalg.matrix_power(x, 3), lambda: [U(3, 3)],
  "linalg", kwargs={"n": 3}, atol=1e-3, rtol=1e-3)
g("slogdet", lambda x: np.stack(np.linalg.slogdet(x)), lambda: [SPD(4)],
  "linalg", atol=1e-4, rtol=1e-4)
g("det", np.linalg.det, lambda: [SPD(3)], "linalg", grad=True,
  atol=1e-3, rtol=1e-3)
g("matrix_transpose", lambda x: np.swapaxes(x, -2, -1), lambda: [U(3, 4)],
  "linalg", grad=True)
g("cov", lambda x: np.cov(x), lambda: [U(3, 8)], "linalg", atol=1e-4)
g("corrcoef", lambda x: np.corrcoef(x), lambda: [U(3, 8)], "linalg",
  atol=1e-4)
g("cross", lambda a, b_: np.cross(a, b_), lambda: [U(4, 3), U(4, 3, seed=1)],
  "linalg", kwargs={"axis": 1}, grad=True)
g("householder_product", None, lambda: [U(4, 3), POS(3, seed=1)], "linalg",
  check=_chk_householder_product)
g("lu", None, lambda: [SPD(4)], "linalg", check=_chk_lu)
g("lu_unpack", None, lambda: [SPD(4)], "linalg", check=_chk_lu_unpack,
  op="paddle_tpu.ops.registry._lu_unpack_helper")
g("ormqr", None,
  lambda: [np.tril(U(4, 4)).astype(np.float32), POS(4, seed=1),
           U(4, 2, seed=2)],
  "linalg", check=_chk_ormqr)
g("cond", lambda x: np.linalg.cond(x), lambda: [SPD(4)], "linalg",
  atol=1e-2, rtol=1e-2)
g("cdist", lambda a, b_: np.sqrt(
    ((a[:, None, :] - b_[None, :, :]) ** 2).sum(-1)),
  lambda: [U(4, 3), U(5, 3, seed=1)], "linalg", grad=True, atol=1e-4)
g("pca_lowrank", None, lambda: [U(6, 4)], "linalg", kind="smoke",
  reason="randomized algorithm (RNG-dependent subspace)")
g("svd_lowrank", None, lambda: [U(6, 4)], "linalg", kind="smoke",
  reason="randomized algorithm (RNG-dependent subspace)")
g("matrix_exp", lambda x: __import__("scipy.linalg", fromlist=["expm"]).expm(x),
  lambda: [U(4, 4)], "linalg", grad=True, atol=1e-4, rtol=1e-4)
g("histogram", lambda x: np.histogram(x, 10)[0], lambda: [U(30)], "linalg",
  kwargs={"bins": 10})
g("bincount", lambda x: np.bincount(x), lambda: [I(20, hi=6)], "linalg")

# ---- logic -------------------------------------------------------------------
b("equal", np.equal, lambda: [I(3, 4), I(3, 4)], grad=False, cat="logic")
b("not_equal", np.not_equal, lambda: [I(3, 4), I(3, 4, seed=1)], grad=False,
  cat="logic")
b("greater_than", np.greater, grad=False, cat="logic")
b("greater_equal", np.greater_equal, grad=False, cat="logic")
b("less_than", np.less, grad=False, cat="logic")
b("less_equal", np.less_equal, grad=False, cat="logic")
alias("less", "less_than", "logic")
b("logical_and", np.logical_and, lambda: [B(3, 4), B(3, 4, seed=1)],
  grad=False, cat="logic")
b("logical_or", np.logical_or, lambda: [B(3, 4), B(3, 4, seed=1)],
  grad=False, cat="logic")
b("logical_xor", np.logical_xor, lambda: [B(3, 4), B(3, 4, seed=1)],
  grad=False, cat="logic")
u("logical_not", np.logical_not, lambda: [B(3, 4)], grad=False, cat="logic")
b("bitwise_and", np.bitwise_and, lambda: [I(3, 4), I(3, 4, seed=1)],
  grad=False, cat="logic")
b("bitwise_or", np.bitwise_or, lambda: [I(3, 4), I(3, 4, seed=1)],
  grad=False, cat="logic")
b("bitwise_xor", np.bitwise_xor, lambda: [I(3, 4), I(3, 4, seed=1)],
  grad=False, cat="logic")
u("bitwise_not", np.bitwise_not, lambda: [I(3, 4)], grad=False, cat="logic")
alias("bitwise_invert", "bitwise_not", "logic")
b("bitwise_left_shift", np.left_shift, lambda: [I(3, 4), I(3, 4, lo=0, hi=3,
                                                           seed=1)],
  grad=False, cat="logic")
b("bitwise_right_shift", np.right_shift, lambda: [I(3, 4), I(3, 4, lo=0, hi=3,
                                                             seed=1)],
  grad=False, cat="logic")
g("equal_all", lambda a, b_: np.array_equal(a, b_), lambda: [I(3), I(3)],
  "logic")
g("isclose", np.isclose, lambda: [U(3, 4), U(3, 4)], "logic")
g("allclose", np.allclose, lambda: [U(3, 4), U(3, 4)], "logic")
g("where", np.where, lambda: [B(3, 4), U(3, 4), U(3, 4, seed=1)], "logic")
g("is_empty", lambda x: x.size == 0, lambda: [U(3)], "logic")

# ---- manipulation ------------------------------------------------------------
g("reshape", lambda x: x.reshape(4, 3), lambda: [U(3, 4)], "manip",
  kwargs={"shape": [4, 3]}, grad=True)
g("transpose", lambda x: x.transpose(1, 0), lambda: [U(3, 4)], "manip",
  kwargs={"perm": [1, 0]}, grad=True)
g("t", lambda x: x.T, lambda: [U(3, 4)], "manip", grad=True)
g("moveaxis", lambda x: np.moveaxis(x, 0, 1), lambda: [U(3, 4)], "manip",
  kwargs={"source": 0, "destination": 1})
g("swapaxes", lambda x: np.swapaxes(x, 0, 1), lambda: [U(3, 4)], "manip",
  kwargs={"axis0": 0, "axis1": 1})
g("squeeze", lambda x: np.squeeze(x, 1), lambda: [U(3, 1, 4)], "manip",
  kwargs={"axis": 1}, grad=True)
g("unsqueeze", lambda x: x[:, None], lambda: [U(3, 4)], "manip",
  kwargs={"axis": 1}, grad=True)
g("flatten", lambda x: x.reshape(-1), lambda: [U(3, 4)], "manip", grad=True)
g("tile", lambda x: np.tile(x, [2, 3]), lambda: [U(3, 4)], "manip",
  kwargs={"repeat_times": [2, 3]})
g("expand", lambda x: np.broadcast_to(x, (3, 4)), lambda: [U(1, 4)], "manip",
  kwargs={"shape": [3, 4]})
g("broadcast_to", lambda x: np.broadcast_to(x, (3, 4)), lambda: [U(1, 4)],
  "manip", kwargs={"shape": [3, 4]})
g("expand_as", lambda x, y: np.broadcast_to(x, y.shape),
  lambda: [U(1, 4), U(3, 4, seed=1)], "manip")
g("flip", lambda x: np.flip(x, 1), lambda: [U(3, 4)], "manip",
  kwargs={"axis": 1})
alias("reverse", "flip", "manip")
g("rot90", lambda x: np.rot90(x), lambda: [U(3, 4)], "manip")
g("roll", lambda x: np.roll(x, 2), lambda: [U(3, 4)], "manip",
  kwargs={"shifts": 2})
g("concat", lambda xs: np.concatenate(xs, 0), lambda: [[U(2, 3), U(3, 3,
                                                                   seed=1)]],
  "manip")
g("stack", lambda xs: np.stack(xs, 0), lambda: [[U(2, 3), U(2, 3, seed=1)]],
  "manip")
g("hstack", lambda xs: np.hstack(xs), lambda: [[U(2, 3), U(2, 3, seed=1)]],
  "manip")
g("vstack", lambda xs: np.vstack(xs), lambda: [[U(2, 3), U(2, 3, seed=1)]],
  "manip")
g("dstack", lambda xs: np.dstack(xs), lambda: [[U(2, 3), U(2, 3, seed=1)]],
  "manip")
g("column_stack", lambda xs: np.column_stack(xs), lambda: [[U(4), U(4, seed=1)]],
  "manip")
alias("row_stack", "vstack", "manip")
g("cartesian_prod", lambda xs: np.stack(
    [g.reshape(-1) for g in np.meshgrid(*xs, indexing="ij")], -1),
  lambda: [[U(3), U(2, seed=1)]], "manip")
g("crop", lambda x: x[1:3, 1:3], lambda: [U(4, 4)], "manip",
  kwargs={"shape": [2, 2], "offsets": [1, 1]})
g("positive", lambda x: +x, lambda: [U(3, 4)], "math", grad=True)
g("numel", lambda x: np.asarray(x.size, np.int32), lambda: [U(3, 4)], "manip")
g("shape", lambda x: np.asarray(x.shape, np.int32), lambda: [U(3, 4)], "manip")
g("standard_gamma", None, lambda: [POS(3, 4)], "random", kind="smoke",
  reason="RNG-valued output")
g("split", lambda x: np.split(x, 3, 0), lambda: [U(6, 3)], "manip",
  kwargs={"num_or_sections": 3})
g("chunk", lambda x: np.split(x, 2, 0), lambda: [U(6, 3)], "manip",
  kwargs={"chunks": 2})
g("tensor_split", lambda x: np.array_split(x, 3), lambda: [U(7)], "manip",
  kwargs={"num_or_indices": 3})
g("hsplit", lambda x: np.hsplit(x, 2), lambda: [U(4, 6)], "manip",
  kwargs={"num_or_indices": 2})
g("vsplit", lambda x: np.vsplit(x, 2), lambda: [U(6, 4)], "manip",
  kwargs={"num_or_indices": 2})
g("dsplit", lambda x: np.dsplit(x, 2), lambda: [U(2, 3, 6)], "manip",
  kwargs={"num_or_indices": 2})
g("unbind", lambda x: [x[i] for i in range(x.shape[0])], lambda: [U(3, 4)],
  "manip")
g("unstack", lambda x: [x[i] for i in range(x.shape[0])], lambda: [U(3, 4)],
  "manip")
g("unflatten", lambda x: x.reshape(3, 2, 2), lambda: [U(3, 4)], "manip",
  kwargs={"axis": 1, "shape": [2, 2]})
g("gather", lambda x: x[[0, 2]], lambda: [U(4, 3)], "manip",
  kwargs={"index": np.array([0, 2], np.int32)})
g("gather_nd", lambda x: x[[0, 2], [1, 2]], lambda: [U(3, 4)], "manip",
  kwargs={"index": np.array([[0, 1], [2, 2]], np.int32)})
g("take", lambda x: x.reshape(-1)[[1, 5, 7]], lambda: [U(3, 4)], "manip",
  kwargs={"index": np.array([1, 5, 7], np.int32)})
g("take_along_axis",
  lambda x: np.take_along_axis(x, np.zeros((3, 1), np.int64), 1),
  lambda: [U(3, 4)], "manip",
  kwargs={"indices": np.zeros((3, 1), np.int32), "axis": 1})


def _put_along_axis_ref(x):
    out = np.asarray(x).copy()
    np.put_along_axis(out, np.zeros((3, 1), np.int64), 9.0, 1)
    return out


g("put_along_axis", _put_along_axis_ref, lambda: [U(3, 4)], "manip",
  kwargs={"indices": np.zeros((3, 1), np.int32), "values": 9.0, "axis": 1})
g("index_select", lambda x: x[[0, 2]], lambda: [U(4, 3)], "manip",
  kwargs={"index": np.array([0, 2], np.int32)})
g("index_sample",
  lambda x: np.take_along_axis(x, np.zeros((3, 2), np.int64), 1),
  lambda: [U(3, 4)], "manip",
  kwargs={"index": np.zeros((3, 2), np.int32)})


def _index_add_ref():
    out = U(4, 3).copy()
    np.add.at(out, [0, 2], np.ones((2, 3), np.float32))
    return out


g("index_add", lambda: _index_add_ref(), lambda: [], "manip",
  op="paddle_tpu.ops.registry._index_add_smoke")


def _with_rows_set(x, rows, value):
    out = np.asarray(x).copy()
    out[rows] = value
    return out


g("index_put", lambda x: _with_rows_set(x, [0, 1], np.ones((2, 3))),
  lambda: [U(4, 3)], "manip",
  kwargs={"indices": (np.array([0, 1], np.int32),),
          "value": np.ones((2, 3), np.float32)})
g("index_fill", lambda x: _with_rows_set(x, [0, 2], 7.0),
  lambda: [U(4, 3)], "manip",
  kwargs={"index": np.array([0, 2], np.int32), "axis": 0, "value": 7.0})
g("scatter", lambda x: _with_rows_set(x, [1, 0], np.ones((2, 3))),
  lambda: [U(4, 3)], "manip",
  kwargs={"index": np.array([1, 0], np.int32),
          "updates": np.ones((2, 3), np.float32)})


def _scatter_nd_ref():
    out = np.zeros((5, 3), np.float32)
    np.add.at(out, [1, 3], np.ones((2, 3), np.float32))
    return out


g("scatter_nd", lambda: _scatter_nd_ref(), lambda: [], "manip",
  op="paddle_tpu.ops.registry._scatter_nd_smoke")


def _scatter_nd_add_ref(x):
    out = np.asarray(x).copy()
    np.add.at(out, [0, 2], np.ones((2, 3), np.float32))
    return out


g("scatter_nd_add", _scatter_nd_add_ref, lambda: [U(4, 3)], "manip",
  kwargs={"index": np.array([[0], [2]], np.int32),
          "updates": np.ones((2, 3), np.float32)})
def _slice_scatter_ref(x, src):
    out = np.asarray(x).copy()
    out[:, 2:4] = src
    return out


g("slice_scatter", _slice_scatter_ref,
  lambda: [U(4, 6), np.zeros((4, 2), np.float32)],
  "manip", kwargs={"axes": [1], "starts": [2], "ends": [4], "strides": [1]})
g("select_scatter", lambda x, src: _with_rows_set(x, 1, src),
  lambda: [U(4, 6), np.zeros((6,), np.float32)],
  "manip", kwargs={"axis": 0, "index": 1})


def _diagonal_scatter_ref(x, src):
    out = np.asarray(x).copy()
    out[np.arange(4), np.arange(4)] = src
    return out


g("diagonal_scatter", _diagonal_scatter_ref,
  lambda: [U(4, 4), np.zeros((4,), np.float32)], "manip")


def _masked_scatter_ref(x, mask, src):
    out = np.asarray(x).copy()
    out[mask] = src[:mask.sum()]
    return out


g("masked_scatter", _masked_scatter_ref,
  lambda: [U(3, 4), B(3, 4, seed=1), U(12, seed=2)], "manip")
g("masked_fill", lambda x, m: np.where(m, 0.0, x),
  lambda: [U(3, 4), B(3, 4, seed=1)], "manip", kwargs={"value": 0.0})
g("masked_select", lambda x, m: x[m],
  lambda: [U(3, 4), B(3, 4, seed=1)], "manip")


def _fill_diagonal_ref(x):
    out = np.asarray(x).copy()
    np.fill_diagonal(out, 0.0)
    return out


g("fill_diagonal", _fill_diagonal_ref, lambda: [U(4, 4)], "manip",
  kwargs={"value": 0.0})
g("repeat_interleave", lambda x: np.repeat(x, 2, 1), lambda: [U(3, 4)],
  "manip", kwargs={"repeats": 2, "axis": 1})
g("unique", None, lambda: [I(10, hi=4)], "manip", check=_chk_unique)
g("unique_consecutive",
  lambda x: x[np.concatenate([[True], np.diff(x) != 0])],
  lambda: [np.array([1, 1, 2, 2, 3, 1], np.int32)], "manip")
g("pad", lambda x: np.pad(x, ((1, 1), (2, 2))), lambda: [U(3, 4)], "manip",
  kwargs={"pad": [1, 1, 2, 2]})
g("unfold", lambda x: np.stack([x[0:4], x[2:6], x[4:8]]), lambda: [U(8)],
  "manip", kwargs={"axis": 0, "size": 4, "step": 2})
g("as_strided", lambda x: x.reshape(3, 4), lambda: [U(12)], "manip",
  kwargs={"shape": [3, 4], "stride": [4, 1]})
g("view", lambda x: x.reshape(4, 3), lambda: [U(3, 4)], "manip",
  kwargs={"shape_or_dtype": [4, 3]})
g("view_as", lambda x, y: x.reshape(y.shape),
  lambda: [U(3, 4), U(4, 3, seed=1)], "manip")
g("atleast_1d", np.atleast_1d, lambda: [np.float32(3.0)], "manip")
g("atleast_2d", np.atleast_2d, lambda: [U(3)], "manip")
g("atleast_3d", np.atleast_3d, lambda: [U(3, 4)], "manip")
g("broadcast_tensors",
  lambda xs: [np.broadcast_to(x, (3, 4)) for x in xs],
  lambda: [[U(1, 4), U(3, 1, seed=1)]], "manip")
g("broadcast_shape", None, None, "manip",
  check=lambda raw, out: np.testing.assert_array_equal(
      np.asarray(out), [3, 4]),
  op="paddle_tpu.ops.registry._broadcast_shape_smoke")
g("cast", lambda x: x.astype(np.int32), lambda: [U(3, 4)], "manip",
  kwargs={"dtype": "int32"})
g("as_complex", lambda x: x[..., 0] + 1j * x[..., 1], lambda: [U(3, 2)],
  "manip")
g("as_real", lambda: np.stack(
    [U(3, 2)[:, 0], U(3, 2)[:, 1]], -1),
  lambda: [], "manip", op="paddle_tpu.ops.registry._as_real_smoke")
g("slice", lambda x: x[:, 1:4], lambda: [U(4, 6)], "manip",
  kwargs={"axes": [1], "starts": [1], "ends": [4]})
g("strided_slice", lambda x: x[:, 0:6:2], lambda: [U(4, 6)], "manip",
  kwargs={"axes": [1], "starts": [0], "ends": [6], "strides": [2]})
g("shard_index",
  lambda x: np.where((x // 4) == 0, x % 4, -1),
  lambda: [I(4, 1, hi=8)], "manip",
  kwargs={"index_num": 8, "nshards": 2, "shard_id": 0})
g("tensordot", lambda a, b_: np.tensordot(a, b_, 1),
  lambda: [U(3, 4), U(4, 5, seed=1)], "manip", kwargs={"axes": 1},
  atol=1e-4, rtol=1e-4)
g("rank", lambda x: np.asarray(x.ndim, np.int32), lambda: [U(3, 4)], "manip")
def _multiplex_ref():
    a, b_, idx = U(3, 4), U(3, 4, seed=1), I(3, 1, hi=2)
    return np.where(idx == 0, a, b_)


g("multiplex", lambda: _multiplex_ref(), lambda: [], "manip",
  op="paddle_tpu.ops.registry._multiplex_smoke")
g("add_n", lambda xs: xs[0] + xs[1], lambda: [[U(3, 4), U(3, 4, seed=1)]],
  "math")

# ---- search / sort -----------------------------------------------------------
g("argmax", np.argmax, lambda: [U(3, 4)], "search")
g("argmin", np.argmin, lambda: [U(3, 4)], "search")
g("argsort", lambda x: np.argsort(x, -1), lambda: [U(3, 4)], "search")
g("sort", lambda x: np.sort(x, -1), lambda: [U(3, 4)], "search")
g("topk",
  lambda x: (np.sort(x, -1)[..., ::-1][..., :2],
             np.argsort(-x, -1)[..., :2]),
  lambda: [U(3, 6)], "search", kwargs={"k": 2})
g("kthvalue",
  lambda x: (np.sort(x, -1)[..., 1], np.argsort(x, -1)[..., 1]),
  lambda: [U(3, 6)], "search", kwargs={"k": 2})
g("mode",
  lambda x: (__import__("scipy.stats", fromlist=["mode"]).mode(
      x, axis=-1, keepdims=False).mode,
      __import__("scipy.stats", fromlist=["mode"]).mode(
          x, axis=-1, keepdims=False).count.astype(np.int64)),
  lambda: [I(3, 6, hi=3)], "search")
g("nonzero", lambda x: np.stack(np.nonzero(x), -1),
  lambda: [I(3, 4, hi=2)], "search")
g("searchsorted", lambda a, v: np.searchsorted(a, v),
  lambda: [np.sort(U(8)), U(5, seed=1)], "search")
g("bucketize", lambda x, e: np.digitize(x, e),
  lambda: [U(6), np.sort(U(4, seed=1))], "search",
  op=lambda x, e: __import__("paddle_tpu.ops", fromlist=["bucketize"]
                             ).bucketize(x, e))
g("top_p_sampling", None,
  lambda: [np.full((2, 16), 1 / 16, np.float32), np.array([[0.5], [0.9]],
                                                          np.float32)],
  "search", kind="smoke", reason="RNG-valued output (categorical draw)")

# ---- stat --------------------------------------------------------------------
g("var", lambda x: np.var(x, ddof=1), lambda: [U(3, 8)], "stat", atol=1e-4)
g("std", lambda x: np.std(x, ddof=1), lambda: [U(3, 8)], "stat", atol=1e-4)
g("median", np.median, lambda: [U(3, 5)], "stat")
g("nanmedian", np.nanmedian, lambda: [U(3, 5)], "stat")
g("quantile", lambda x: np.quantile(x, 0.3), lambda: [U(24)], "stat",
  kwargs={"q": 0.3}, atol=1e-4)
g("nanquantile", lambda x: np.nanquantile(x, 0.3), lambda: [U(24)], "stat",
  kwargs={"q": 0.3}, atol=1e-4)

# ---- creation ----------------------------------------------------------------
g("arange", lambda: np.arange(0, 10, 2, np.float32), lambda: [], "creation",
  kwargs={"start": 0, "end": 10, "step": 2, "dtype": "float32"})
g("linspace", lambda: np.linspace(0, 1, 5).astype(np.float32), lambda: [],
  "creation", kwargs={"start": 0, "stop": 1, "num": 5}, atol=1e-6)
g("logspace", lambda: np.logspace(0, 2, 4).astype(np.float32), lambda: [],
  "creation", kwargs={"start": 0, "stop": 2, "num": 4}, rtol=1e-4)
g("eye", lambda: np.eye(4, dtype=np.float32), lambda: [], "creation",
  kwargs={"num_rows": 4})
g("zeros", lambda: np.zeros((2, 3), np.float32), lambda: [], "creation",
  kwargs={"shape": [2, 3]})
g("ones", lambda: np.ones((2, 3), np.float32), lambda: [], "creation",
  kwargs={"shape": [2, 3]})
g("full", lambda: np.full((2, 3), 7.0, np.float32), lambda: [], "creation",
  kwargs={"shape": [2, 3], "fill_value": 7.0})
g("zeros_like", np.zeros_like, lambda: [U(3, 4)], "creation")
g("ones_like", np.ones_like, lambda: [U(3, 4)], "creation")
g("full_like", lambda x: np.full_like(x, 5.0), lambda: [U(3, 4)], "creation",
  kwargs={"fill_value": 5.0})
g("empty", None, lambda: [], "creation", kind="smoke",
  kwargs={"shape": [2, 3]},
  reason="uninitialized values by contract; only shape/dtype are defined")
g("empty_like", None, lambda: [U(3, 4)], "creation", kind="smoke",
  reason="uninitialized values by contract; only shape/dtype are defined")
g("tril", np.tril, lambda: [U(4, 4)], "creation", grad=True)
g("triu", np.triu, lambda: [U(4, 4)], "creation", grad=True)
g("diag", np.diag, lambda: [U(4)], "creation")
g("diagflat", np.diagflat, lambda: [U(2, 2)], "creation")
def _diag_embed_ref(x):
    out = np.zeros(x.shape + (x.shape[-1],), x.dtype)
    for i in range(x.shape[0]):
        np.fill_diagonal(out[i], x[i])
    return out


g("diag_embed", _diag_embed_ref, lambda: [U(3, 4)], "creation")
g("tril_indices", lambda: np.stack(np.tril_indices(4)).astype(np.int64),
  lambda: [], "creation", kwargs={"row": 4, "col": 4})
g("triu_indices", lambda: np.stack(np.triu_indices(4)).astype(np.int64),
  lambda: [], "creation", kwargs={"row": 4})
g("meshgrid", lambda x, y: np.meshgrid(x, y, indexing="ij"),
  lambda: [U(3), U(4, seed=1)], "creation")
g("clone", lambda x: x.copy(), lambda: [U(3, 4)], "creation", grad=True)
g("assign", lambda x: x.copy(), lambda: [U(3, 4)], "creation")
g("to_tensor", lambda x: x, lambda: [U(3, 4)], "creation")
g("complex", lambda re, im: re + 1j * im, lambda: [U(3, 4), U(3, 4, seed=1)],
  "creation")
g("polar", lambda r, t: r * np.cos(t) + 1j * r * np.sin(t),
  lambda: [POS(3, 4), U(3, 4, seed=1)], "creation", atol=1e-4)
g("create_tensor", None, lambda: [], "creation", kind="smoke",
  kwargs={"dtype": "float32"},
  reason="empty container by contract; only dtype is defined")
g("create_parameter", None, lambda: [], "creation", kind="smoke",
  kwargs={"shape": [3, 4], "dtype": "float32"},
  reason="RNG-valued (default initializer draws from the global seed)")
g("is_tensor", None, None, "logic",
  check=lambda raw, out: np.testing.assert_equal(_tonp(out).shape, (2,)),
  op="paddle_tpu.ops.registry._is_tensor_smoke")
g("is_complex", lambda x: False, lambda: [U(2)], "logic")
g("is_integer", lambda x: True, lambda: [I(2)], "logic")
g("is_floating_point", lambda x: True, lambda: [U(2)], "logic")

# ---- random (smoke: distributional sanity lives in test_ops) -----------------
for _name, _kw in [
    ("uniform", {"shape": [64]}), ("rand", {"shape": [64]}),
    ("randn", {"shape": [64]}), ("standard_normal", {"shape": [64]}),
    ("normal", {"shape": [64]}), ("gaussian", {"shape": [64]}),
    ("randint", {"low": 0, "high": 5, "shape": [64]}),
    ("randperm", {"n": 16}), ("poisson", None), ("bernoulli", None),
    ("multinomial", None), ("binomial", None), ("log_normal", {"shape": [64]}),
]:
    _why = "RNG-valued output (distributional checks live in test_ops)"
    if _kw is not None:
        smoke(_name, lambda: [], "random", kwargs=_kw, reason=_why)
    elif _name == "poisson":
        smoke(_name, lambda: [POS(16)], "random", reason=_why)
    elif _name == "binomial":
        smoke(_name, lambda: [np.full((8,), 10.0, np.float32),
                              PROB(8, seed=1)], "random", reason=_why)
    else:
        smoke(_name, lambda: [PROB(16)], "random", reason=_why)
smoke("randint_like", lambda: [I(8)], "random", kwargs={"low": 0, "high": 5},
      reason="RNG-valued output")
smoke("shuffle", lambda: [U(8)], "random",
      reason="RNG-valued output (random permutation)")

# ---- fft ---------------------------------------------------------------------
for _n, _ref in [("fft", np.fft.fft), ("ifft", np.fft.ifft),
                 ("rfft", np.fft.rfft), ("irfft", np.fft.irfft),
                 ("hfft", np.fft.hfft), ("ihfft", np.fft.ihfft)]:
    g(_n, _ref, lambda: [U(4, 8)], "fft", op=f"paddle_tpu.fft.{_n}",
      atol=1e-4, rtol=1e-4)
for _n, _ref in [("fft2", np.fft.fft2), ("ifft2", np.fft.ifft2),
                 ("rfft2", np.fft.rfft2), ("irfft2", np.fft.irfft2)]:
    g(_n, _ref, lambda: [U(4, 8)], "fft", op=f"paddle_tpu.fft.{_n}",
      atol=1e-4, rtol=1e-4)
for _n, _ref in [("fftn", np.fft.fftn), ("ifftn", np.fft.ifftn),
                 ("rfftn", np.fft.rfftn), ("irfftn", np.fft.irfftn)]:
    g(_n, _ref, lambda: [U(2, 4, 8)], "fft", op=f"paddle_tpu.fft.{_n}",
      atol=1e-4, rtol=1e-4)
g("fftshift", np.fft.fftshift, lambda: [U(8)], "fft",
  op="paddle_tpu.fft.fftshift")
g("ifftshift", np.fft.ifftshift, lambda: [U(8)], "fft",
  op="paddle_tpu.fft.ifftshift")
g("fftfreq", lambda: np.fft.fftfreq(8).astype(np.float32), lambda: [], "fft",
  op="paddle_tpu.fft.fftfreq", kwargs={"n": 8})
g("rfftfreq", lambda: np.fft.rfftfreq(8).astype(np.float32), lambda: [],
  "fft", op="paddle_tpu.fft.rfftfreq", kwargs={"n": 8})
g("hfft2", lambda x: np.fft.fft(np.fft.hfft(x, axis=-1), axis=-2).real,
  lambda: [U(4, 8)], "fft", op="paddle_tpu.fft.hfft2", atol=1e-3, rtol=1e-3)
g("ihfft2", lambda x: np.fft.ifft(np.fft.ihfft(x, axis=-1), axis=-2),
  lambda: [U(4, 8)], "fft", op="paddle_tpu.fft.ihfft2", atol=1e-4, rtol=1e-4)
g("hfftn", lambda x: np.fft.fft(np.fft.hfft(x, axis=-1), axis=0).real,
  lambda: [U(4, 8)], "fft", op="paddle_tpu.fft.hfftn", atol=1e-3, rtol=1e-3)
g("ihfftn", lambda x: np.fft.ifft(np.fft.ihfft(x, axis=-1), axis=0),
  lambda: [U(4, 8)], "fft", op="paddle_tpu.fft.ihfftn", atol=1e-4, rtol=1e-4)

# ---- signal ------------------------------------------------------------------
def _stft_ref(x):
    """n_fft=16, hop=4, rectangular window, center-reflect pad, onesided."""
    a = np.pad(x, [(0, 0), (8, 8)], mode="reflect")
    n_frames = 1 + (a.shape[-1] - 16) // 4
    frames = np.stack([a[:, i * 4:i * 4 + 16] for i in range(n_frames)], 1)
    return np.moveaxis(np.fft.rfft(frames, axis=-1), 1, -1)


g("stft", _stft_ref, lambda: [U(2, 64)], "signal",
  op="paddle_tpu.signal.stft", kwargs={"n_fft": 16}, atol=1e-3, rtol=1e-3)
g("istft", None, lambda: [U(2, 64)], "signal", check=_chk_istft,
  op="paddle_tpu.ops.registry._istft_roundtrip")


def _frame_ref(x):
    return np.stack([x[:, i * 4:i * 4 + 8] for i in range(7)], -1)


g("frame", _frame_ref, lambda: [U(2, 32)], "signal",
  op="paddle_tpu.signal.frame",
  kwargs={"frame_length": 8, "hop_length": 4})


def _overlap_add_ref(x):
    n = 4 * (x.shape[-1] - 1) + 8
    out = np.zeros(x.shape[:-2] + (n,), x.dtype)
    for i in range(x.shape[-1]):
        out[..., i * 4:i * 4 + 8] += x[..., :, i]
    return out


g("overlap_add", _overlap_add_ref, lambda: [U(2, 8, 7)], "signal",
  op="paddle_tpu.signal.overlap_add", kwargs={"hop_length": 4})

# ---- in-place surface (mechanical rebind of the out-of-place op) ------------
_INPLACE_SURFACE = [
    "add", "subtract", "multiply", "divide", "scale", "clip", "floor", "ceil",
    "round", "exp", "sqrt", "rsqrt", "reciprocal", "tanh", "sigmoid", "abs",
    "neg", "pow", "remainder", "lerp", "squeeze", "unsqueeze", "flatten",
    "masked_fill", "index_put", "fill_diagonal", "cast", "scatter", "where",
    "asin", "cumsum", "cumprod", "logit", "log", "log2", "log10", "square",
    "multigammaln", "nan_to_num", "hypot", "floor_divide", "mod", "log1p",
    "addmm", "lgamma", "gammaincc", "gammainc", "equal", "greater_equal",
    "greater_than", "less_equal", "less_than", "less", "logical_and",
    "logical_not", "logical_or", "logical_xor", "not_equal", "tan", "gammaln",
    "digamma", "trunc", "frac", "bitwise_and", "bitwise_or", "bitwise_xor",
    "bitwise_not", "bitwise_invert", "atanh", "gcd", "lcm", "erfinv",
    "put_along_axis", "ldexp", "i0", "polygamma", "renorm", "tril", "triu",
    "acos", "atan", "cos", "cosh", "sin", "sinc", "sinh", "acosh", "asinh",
    "copysign", "bitwise_left_shift", "bitwise_right_shift", "index_fill",
    "masked_scatter", "t", "floor_mod", "uniform", "normal", "exponential",
    "bernoulli", "cauchy", "geometric", "log_normal", "zero", "fill", "set",
    "reshape", "transpose",
]
for _nm in _INPLACE_SURFACE:
    inplace(_nm + "_", _nm)


# ---- smoke helpers needing special construction ------------------------------
def _lu_unpack_helper(a):
    """lu_unpack needs a packed factorization: factor the sampled matrix
    first so the check can reconstruct it from the SAME raw input."""
    import paddle_tpu as pt
    lu_t, piv = pt.ops.lu(a)
    return pt.ops.lu_unpack(lu_t, piv)


def _scatter_nd_smoke():
    import paddle_tpu as pt
    return pt.ops.scatter_nd(pt.to_tensor(np.array([[1], [3]])),
                             pt.to_tensor(np.ones((2, 3), np.float32)),
                             shape=[5, 3])


def _broadcast_shape_smoke():
    import paddle_tpu as pt
    return pt.ops.broadcast_shape([1, 4], [3, 1])


def _multiplex_smoke():
    import paddle_tpu as pt
    ins = [pt.to_tensor(U(3, 4)), pt.to_tensor(U(3, 4, seed=1))]
    return pt.ops.multiplex(ins, pt.to_tensor(I(3, 1, hi=2)))


def _as_real_smoke():
    import paddle_tpu as pt
    c = pt.ops.as_complex(pt.to_tensor(U(3, 2)))
    return pt.ops.as_real(c)


def _is_tensor_smoke():
    import paddle_tpu as pt
    assert pt.ops.is_tensor(pt.to_tensor(U(2)))
    return pt.to_tensor(U(2))


def _index_add_smoke():
    import paddle_tpu as pt
    return pt.ops.index_add(pt.to_tensor(U(4, 3)),
                            pt.to_tensor(np.array([0, 2])), 0,
                            pt.to_tensor(np.ones((2, 3), np.float32)))


def _istft_roundtrip(x):
    """Round-trip through stft so the inverse property is checked against
    the SAME raw input the sample produced."""
    import paddle_tpu.signal as S
    spec = S.stft(x, 16)
    return S.istft(spec, 16, length=x.shape[-1])


# fd-grad eligibility for the r5-converted goldens: linear/smooth ops with
# plain float tensor inputs (decompositions, integer/complex outputs and
# list-input ops stay un-graded — op_test's harness can't finite-difference
# those shapes)
for _gname in [
    "expand_as", "masked_fill", "take_along_axis",
    "index_sample", "tensordot", "einsum", "cholesky_solve",
    "triangular_solve", "reduce_as", "unfold", "as_strided",
    "slice", "strided_slice", "slice_scatter", "select_scatter",
    "diagonal_scatter", "fill_diagonal", "index_fill", "index_put",
    "scatter", "scatter_nd_add", "put_along_axis", "gather_nd",
    "split", "chunk", "tensor_split", "hsplit", "vsplit", "dsplit",
    "unbind", "unstack", "frame", "overlap_add",
]:
    REGISTRY[_gname].grad = True


# =============================================================================
# coverage report
# =============================================================================
def coverage_report(verbose=False):
    """Surface parity summary vs the reference tensor_method_func + namespaces."""
    import paddle_tpu as pt
    import paddle_tpu.ops as O
    by_kind = {}
    by_cat = {}
    for s in REGISTRY.values():
        by_kind[s.kind] = by_kind.get(s.kind, 0) + 1
        by_cat[s.category] = by_cat.get(s.category, 0) + 1
    total = len(REGISTRY)
    report = {
        "registered_ops": total,
        "by_kind": by_kind,
        "by_category": by_cat,
        "golden_tested": by_kind.get("golden", 0),
        "grad_checked": sum(1 for s in REGISTRY.values() if s.grad),
        # each remaining execute-only entry with its documented excuse
        "smoke_reasons": {s.name: s.reason for s in REGISTRY.values()
                          if s.kind == "smoke"},
    }
    if verbose:
        import json
        print(json.dumps(report, indent=2, sort_keys=True))  # graftlint: disable=no-adhoc-telemetry
    return report


if __name__ == "__main__":
    coverage_report(verbose=True)
