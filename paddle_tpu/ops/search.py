"""Search/sort ops (reference: python/paddle/tensor/search.py).

Dynamic-output-shape ops (nonzero, masked_select) run host-side in eager and raise
under program capture — same bucketing policy SURVEY §7 prescribes.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import convert_dtype
    from ..core.dispatch import apply_op
    dt = convert_dtype(dtype)

    def f(a):
        if axis is None:
            out = jnp.argmax(a.reshape(-1))
            out = out.reshape((1,) * a.ndim) if keepdim else out
        else:
            out = jnp.argmax(a, axis=axis, keepdims=keepdim)
        return out.astype(dt)
    return apply_op("argmax", f, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import convert_dtype
    from ..core.dispatch import apply_op
    dt = convert_dtype(dtype)

    def f(a):
        if axis is None:
            out = jnp.argmin(a.reshape(-1))
            out = out.reshape((1,) * a.ndim) if keepdim else out
        else:
            out = jnp.argmin(a, axis=axis, keepdims=keepdim)
        return out.astype(dt)
    return apply_op("argmin", f, x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    from ..core.dispatch import apply_op

    def f(a):
        out = jnp.argsort(-a if descending else a, axis=axis,
                          stable=stable or descending)
        return out.astype(jnp.int64)
    return apply_op("argsort", f, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis, stable=stable)
        return jnp.flip(out, axis=axis) if descending else out
    return apply_op("sort", f, x)


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    kk = int(unwrap(k))
    def f(a):
        ax = axis if axis is not None else a.ndim - 1
        ax = ax % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        vals, idx = jax.lax.top_k(moved if largest else -moved, kk)
        if not largest:
            vals = -vals
        return jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax)
    out_v, out_i = apply_op("topk", f, x)
    out_i.stop_gradient = True
    return out_v, Tensor(out_i._data.astype(jnp.int64))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        ax = axis % a.ndim
        sorted_v = jnp.sort(a, axis=ax)
        sorted_i = jnp.argsort(a, axis=ax)
        v = jnp.take(sorted_v, k - 1, axis=ax)
        i = jnp.take(sorted_i, k - 1, axis=ax)
        if keepdim:
            v, i = jnp.expand_dims(v, ax), jnp.expand_dims(i, ax)
        return v, i
    v, i = apply_op("kthvalue", f, x)
    i.stop_gradient = True
    return v, Tensor(i._data.astype(jnp.int64))


def mode(x, axis=-1, keepdim=False, name=None):
    a = np.asarray(unwrap(x))
    from scipy import stats as _stats  # available via numpy ecosystem
    m = _stats.mode(a, axis=axis, keepdims=keepdim)
    return Tensor(jnp.asarray(m.mode)), Tensor(jnp.asarray(m.count.astype(np.int64)))


def nonzero(x, as_tuple=False):
    arr = np.asarray(unwrap(x))
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64)).reshape(-1)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def masked_select(x, mask, name=None):
    arr = np.asarray(unwrap(x))
    m = np.asarray(unwrap(mask))
    return Tensor(jnp.asarray(arr[np.broadcast_to(m, arr.shape)]))


def index_sample(x, index):
    idx = unwrap(index)
    return apply_op("index_sample", lambda a: jnp.take_along_axis(a, idx, axis=1), x)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    seq, v = unwrap(sorted_sequence), unwrap(values)
    side = "right" if right else "left"
    if seq.ndim == 1:
        out = jnp.searchsorted(seq, v, side=side)
    else:
        flat_seq = seq.reshape(-1, seq.shape[-1])
        flat_v = jnp.broadcast_to(v, v.shape).reshape(-1, v.shape[-1])
        out = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(flat_seq, flat_v)
        out = out.reshape(v.shape)
    return Tensor(out.astype(jnp.int32 if out_int32 else jnp.int64))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)
