"""Linalg decompositions closing the paddle.linalg surface gap (reference:
python/paddle/tensor/linalg.py — lu/lu_unpack, ormqr, cond, cholesky_inverse,
cdist, low-rank PCA/SVD; kernels phi/kernels/impl/lu_kernel_impl.h etc.)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from . import linalg as _linalg


def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization; pivots are 1-based row-swap indices (LAPACK ipiv
    convention, matching the reference lu kernel)."""
    if not pivot:
        raise NotImplementedError("lu(pivot=False) is not supported on TPU")

    def f(a):
        lu_mat, piv = jax.scipy.linalg.lu_factor(a)
        return lu_mat, (piv + 1).astype(jnp.int32)

    out = apply_op("lu", f, x)
    if get_infos:
        lu_mat, piv = out
        info = Tensor(jnp.zeros(lu_mat.shape[:-2], jnp.int32))
        return lu_mat, piv, info
    return out


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu() output into (P, L, U)."""
    lu_mat = unwrap(x)
    piv = np.asarray(unwrap(y)) - 1       # back to 0-based
    m, n = lu_mat.shape[-2], lu_mat.shape[-1]
    k = min(m, n)

    def f(a):
        L = jnp.tril(a[..., :, :k], -1) + jnp.eye(m, k, dtype=a.dtype)
        U = jnp.triu(a[..., :k, :])
        return L, U

    # permutation matrix from the ipiv row swaps
    def _perm_matrix(ipiv):
        perm = np.arange(m)
        for i, j in enumerate(ipiv):
            perm[i], perm[int(j)] = perm[int(j)], perm[i]
        return np.eye(m, dtype=np.float32)[:, perm]

    if piv.ndim == 1:
        Pt = Tensor(jnp.asarray(_perm_matrix(piv)))
    else:  # batched: build per-batch permutations
        batch = piv.shape[:-1]
        P = np.zeros(batch + (m, m), np.float32)
        for idx in np.ndindex(*batch):
            P[idx] = _perm_matrix(piv[idx])
        Pt = Tensor(jnp.asarray(P))
    L, U = apply_op("lu_unpack", f, x)
    out = []
    if unpack_pivots:
        out.append(Pt)
    if unpack_ludata:
        out.extend([L, U])
    return tuple(out) if len(out) != 1 else out[0]


def ormqr(x, tau, y, left=True, transpose=False, name=None):
    """Multiply y by Q from a householder (geqrf-style) factorization."""
    def f(a, t, other):
        q = jax.lax.linalg.householder_product(a, t)
        qm = jnp.swapaxes(q, -2, -1) if transpose else q
        return qm @ other if left else other @ qm
    return apply_op("ormqr", f, x, tau, y)


def cond(x, p=None, name=None):
    def f(a):
        if p in (None, 2):
            s = jnp.linalg.svd(a, compute_uv=False)
            return s[..., 0] / s[..., -1]
        if p == -2:
            s = jnp.linalg.svd(a, compute_uv=False)
            return s[..., -1] / s[..., 0]
        if p in ("fro", "nuc", 1, -1, np.inf, -np.inf):
            return jnp.linalg.norm(a, ord=p, axis=(-2, -1)) * \
                jnp.linalg.norm(jnp.linalg.inv(a), ord=p, axis=(-2, -1))
        raise ValueError(f"unsupported p for cond: {p}")
    return apply_op("cond", f, x)


def cholesky_inverse(x, upper=False, name=None):
    def f(a):
        eye = jnp.eye(a.shape[-1], dtype=a.dtype)
        # scipy convention flag is `lower`
        return jax.scipy.linalg.cho_solve((a, not upper), eye)
    return apply_op("cholesky_inverse", f, x)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def f(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(d * d, axis=-1) + 1e-30)
        if p == float("inf"):
            return jnp.max(jnp.abs(d), axis=-1)
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    return apply_op("cdist", f, x, y)


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Principal components via (deterministic) SVD — the reference uses a
    randomized range finder; on TPU the dense SVD of the centered matrix is
    exact and fuses fine at these sizes."""
    a = unwrap(x)
    m, n = a.shape[-2], a.shape[-1]
    q = q if q is not None else min(6, m, n)

    def f(arr):
        c = arr - jnp.mean(arr, axis=-2, keepdims=True) if center else arr
        u, s, vh = jnp.linalg.svd(c, full_matrices=False)
        return u[..., :q], s[..., :q], jnp.swapaxes(vh, -2, -1)[..., :q]
    return apply_op("pca_lowrank", f, x)


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    a = unwrap(x)
    q = min(q, a.shape[-2], a.shape[-1])

    def f(arr, *rest):
        c = arr - rest[0] if rest else arr
        u, s, vh = jnp.linalg.svd(c, full_matrices=False)
        return u[..., :q], s[..., :q], jnp.swapaxes(vh, -2, -1)[..., :q]
    args = (x, M) if M is not None else (x,)
    return apply_op("svd_lowrank", f, *args)


def matrix_exp(x, name=None):
    """Matrix exponential (reference: phi matrix_exp kernel / paddle.linalg.
    matrix_exp) via jax.scipy.linalg.expm (Pade + scaling-squaring on MXU
    matmuls)."""
    def f(arr):
        import jax.scipy.linalg as jsl
        a32 = arr.astype(jnp.float32) if arr.dtype == jnp.bfloat16 else arr
        out = jsl.expm(a32)
        return out.astype(arr.dtype)
    return apply_op("matrix_exp", f, x)
