"""einsum (reference: python/paddle/tensor/einsum.py) — delegates to jnp.einsum (MXU)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dispatch import apply_op


def einsum(equation, *operands):
    return apply_op("einsum", lambda *arrs: jnp.einsum(equation, *arrs), *operands)
