"""Comparison / logical / bitwise ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap


def _cmp(name, jfn):
    # through dispatch (not raw jnp) so capture and static replay record it;
    # bool outputs get stop_gradient=True automatically
    def op(x, y, name_=None):
        return apply_op(name, jfn, x, y)
    op.__name__ = name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)

logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)
bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


def logical_not(x, out=None, name=None):
    return apply_op("logical_not", jnp.logical_not, x)


def bitwise_not(x, out=None, name=None):
    return apply_op("bitwise_not", jnp.bitwise_not, x)


def equal_all(x, y, name=None):
    return apply_op("equal_all", jnp.array_equal, x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def all(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op("all", lambda a: jnp.all(a, axis=ax, keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_op("any", lambda a: jnp.any(a, axis=ax, keepdims=keepdim), x)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(unwrap(x), unwrap(y), rtol=rtol, atol=atol, equal_nan=equal_nan))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero
        return tuple(nonzero(condition, as_tuple=True))
    cond = unwrap(condition)
    return apply_op("where", lambda a, b: jnp.where(cond, a, b), x, y)


def where_(condition, x, y, name=None):
    out = where(condition, x, y)
    x._data = out._data
    x._grad_node, x._out_slot = out._grad_node, out._out_slot
    return x


def is_tensor(x):
    return isinstance(x, Tensor)
