"""Statistics ops (reference: python/paddle/tensor/stat.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap


def _ax(axis):
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("var", lambda a: jnp.var(a, axis=_ax(axis), ddof=1 if unbiased else 0,
                                             keepdims=keepdim), x)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("std", lambda a: jnp.std(a, axis=_ax(axis), ddof=1 if unbiased else 0,
                                             keepdims=keepdim), x)


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    def f(a):
        if mode == "avg":
            return jnp.median(a, axis=_ax(axis), keepdims=keepdim)
        # mode == 'min': lower median
        ax = axis if axis is not None else None
        if ax is None:
            flat = jnp.sort(a.reshape(-1))
            return flat[(flat.shape[0] - 1) // 2]
        srt = jnp.sort(a, axis=ax)
        idx = (a.shape[ax] - 1) // 2
        out = jnp.take(srt, idx, axis=ax)
        return jnp.expand_dims(out, ax) if keepdim else out
    return apply_op("median", f, x)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply_op("nanmedian", lambda a: jnp.nanmedian(a, axis=_ax(axis), keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qq = unwrap(q)
    def f(a):
        out = jnp.quantile(a.astype(jnp.float32), jnp.asarray(qq, jnp.float32), axis=_ax(axis),
                           keepdims=keepdim, method=interpolation)
        return out
    return apply_op("quantile", f, x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    qq = unwrap(q)
    return apply_op("nanquantile",
                    lambda a: jnp.nanquantile(a.astype(jnp.float32), jnp.asarray(qq, jnp.float32),
                                              axis=_ax(axis), keepdims=keepdim,
                                              method=interpolation), x)
