"""Manipulation/indexing ops closing the paddle.tensor surface gap (reference:
python/paddle/tensor/manipulation.py — tensor_split family, unstack, take,
unflatten, as_strided, scatter variants; kernels phi/kernels/*)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap
from . import manipulation as _manip
from . import logic as _logic


def reverse(x, axis, name=None):
    return _manip.flip(x, axis)


less = _logic.less_than
bitwise_invert = _logic.bitwise_not


def tensor_split(x, num_or_indices, axis=0, name=None):
    n = x.shape[axis] if hasattr(x, "shape") else None
    if isinstance(num_or_indices, int):
        k = num_or_indices
        base, rem = divmod(n, k)
        sizes = [base + (1 if i < rem else 0) for i in range(k)]
        bounds = np.cumsum([0] + sizes)
    else:
        idx = list(num_or_indices)
        bounds = [0] + idx + [n]
    outs = []
    for a, b in zip(bounds[:-1], bounds[1:]):
        a, b = int(a), int(b)
        outs.append(apply_op("tensor_split",
                             lambda arr, a=a, b=b:
                             jnp.take(arr, jnp.arange(a, b), axis=axis), x))
    return outs


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    return [apply_op("unstack",
                     lambda a, i=i: jnp.take(a, i, axis=axis), x)
            for i in range(n)]


def take(x, index, mode="raise", name=None):
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(f"take mode must be raise/wrap/clip, got {mode}")
    jmode = {"raise": "clip", "wrap": "wrap", "clip": "clip"}[mode]
    return apply_op("take",
                    lambda a, i: jnp.take(a.reshape(-1), i, mode=jmode),
                    x, index)


def unflatten(x, axis, shape, name=None):
    shape = [int(s) for s in (shape.tolist() if isinstance(shape, Tensor)
                              else shape)]

    def f(a):
        ax = axis if axis >= 0 else a.ndim + axis
        new = list(a.shape[:ax]) + shape + list(a.shape[ax + 1:])
        # resolve a single -1
        if -1 in shape:
            known = int(np.prod([s for s in shape if s != -1]))
            new[new.index(-1)] = a.shape[ax] // known
        return a.reshape(new)
    return apply_op("unflatten", f, x)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view via gather on the flat buffer (reference as_strided is a
    metadata-only view; XLA has no aliased strides, so this materializes)."""
    shape = [int(s) for s in shape]
    stride = [int(s) for s in stride]

    def f(a):
        idx = np.asarray(offset)
        for s, st in zip(shape, stride):
            idx = idx[..., None] + np.arange(s) * st
        return a.reshape(-1)[jnp.asarray(idx.reshape(shape))]
    return apply_op("as_strided", f, x)


def view_as(x, other, name=None):
    return _manip.reshape(x, list(other.shape))


def matrix_transpose(x, name=None):
    return apply_op("matrix_transpose", lambda a: jnp.swapaxes(a, -2, -1), x)


def rank(x, name=None):
    return Tensor(jnp.asarray(len(x.shape), jnp.int32))


def is_complex(x):
    return bool(jnp.issubdtype(unwrap(x).dtype, jnp.complexfloating))


def is_integer(x):
    return bool(jnp.issubdtype(unwrap(x).dtype, jnp.integer))


def is_floating_point(x):
    return bool(jnp.issubdtype(unwrap(x).dtype, jnp.floating))


def _slices_for(axes, starts, ends, strides, ndim):
    sl = [slice(None)] * ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        sl[ax] = slice(int(st), int(en), int(sr))
    return tuple(sl)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(a, v):
        sl = _slices_for(axes, starts, ends, strides, a.ndim)
        return a.at[sl].set(v.astype(a.dtype))
    return apply_op("slice_scatter", f, x, value)


def select_scatter(x, values, axis, index, name=None):
    def f(a, v):
        sl = [slice(None)] * a.ndim
        sl[axis] = int(index)
        return a.at[tuple(sl)].set(v.astype(a.dtype))
    return apply_op("select_scatter", f, x, values)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(a, v):
        n, m = a.shape[axis1], a.shape[axis2]
        if offset >= 0:
            k = min(n, m - offset)
            rows, cols = np.arange(k), np.arange(k) + offset
        else:
            k = min(n + offset, m)
            rows, cols = np.arange(k) - offset, np.arange(k)
        moved = jnp.moveaxis(a, (axis1, axis2), (0, 1))
        # v's diagonal dim is last; bring it first to line up with [rows, cols]
        vmoved = jnp.moveaxis(v, -1, 0) if v.ndim == a.ndim - 1 else v
        out = moved.at[rows, cols].set(vmoved.astype(a.dtype))
        return jnp.moveaxis(out, (0, 1), (axis1, axis2))
    return apply_op("diagonal_scatter", f, x, y)


def index_fill(x, index, axis, value, name=None):
    def f(a, i):
        moved = jnp.moveaxis(a, axis, 0)
        out = moved.at[i].set(jnp.asarray(value, a.dtype))
        return jnp.moveaxis(out, 0, axis)
    return apply_op("index_fill", f, x, index)


def masked_scatter(x, mask, value, name=None):
    def f(a, m, v):
        m = jnp.broadcast_to(m, a.shape)
        pos = jnp.cumsum(m.reshape(-1)) - 1
        src = v.reshape(-1)[jnp.clip(pos, 0, v.size - 1)].reshape(a.shape)
        return jnp.where(m, src.astype(a.dtype), a)
    return apply_op("masked_scatter", f, x, mask, value)


def hstack(x, name=None):
    """numpy-compatible horizontal stack (reference tensor/manipulation.py
    hstack)."""
    def f(*arrs):
        return jnp.hstack(arrs)
    return apply_op("hstack", f, *list(x))


def vstack(x, name=None):
    def f(*arrs):
        return jnp.vstack(arrs)
    return apply_op("vstack", f, *list(x))


def dstack(x, name=None):
    def f(*arrs):
        return jnp.dstack(arrs)
    return apply_op("dstack", f, *list(x))


def column_stack(x, name=None):
    def f(*arrs):
        return jnp.column_stack(arrs)
    return apply_op("column_stack", f, *list(x))


def row_stack(x, name=None):
    return vstack(x, name)


def cartesian_prod(x, name=None):
    """Cartesian product of 1-D tensors -> [prod(N_i), k] (reference
    tensor/math.py cartesian_prod)."""
    ts = list(x)

    def f(*arrs):
        if len(arrs) == 1:          # reference returns 1-D for a single input
            return arrs[0].reshape(-1)
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)
    return apply_op("cartesian_prod", f, *ts)


def crop(x, shape=None, offsets=None, name=None):
    """Static crop (reference tensor/creation.py crop): slice `shape` starting
    at `offsets` (defaults: offsets 0; -1 in shape = to the end)."""
    arr_shape = x.shape
    offs = [int(v) for v in (offsets if offsets is not None
                             else [0] * len(arr_shape))]
    tgt = [int(v) for v in (shape if shape is not None else arr_shape)]
    sizes = [arr_shape[i] - offs[i] if tgt[i] == -1 else tgt[i]
             for i in range(len(arr_shape))]

    import jax

    def f(a):
        return jax.lax.dynamic_slice(a, offs, sizes)
    return apply_op("crop", f, x)


def positive(x, name=None):
    """reference tensor/math.py positive: +x (errors on bool like numpy)."""
    if str(getattr(unwrap(x), "dtype", "")) == "bool":
        raise TypeError("positive is not supported for bool tensors")
    return apply_op("positive", lambda a: +a, x)


def shape(x, name=None):
    """reference paddle.shape: the RUNTIME shape as an int32 tensor."""
    return Tensor(jnp.asarray(unwrap(x).shape, jnp.int32))


def numel(x, name=None):
    """reference paddle.numel: element count as a 0-D integer tensor (int32 —
    x64 is disabled on this build, so int64 would narrow anyway)."""
    return Tensor(jnp.asarray(int(np.prod(unwrap(x).shape)), jnp.int32))


def tolist(x):
    """reference paddle.tolist (delegates to Tensor.tolist)."""
    return x.tolist() if isinstance(x, Tensor) else np.asarray(x).tolist()
