"""Math ops (reference: python/paddle/tensor/math.py; kernels phi/kernels/...).

Each op is the jax array-level computation routed through dispatch (autograd +
AMP + capture come for free). Paddle argument names (axis/keepdim/...) preserved.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..core.dispatch import apply_op, defop, unwrap


# ---- elementwise unary -------------------------------------------------------
def _unary(name, jfn):
    def op(x, name_=None, **kw):
        return apply_op(name, (lambda a: jfn(a, **kw)) if kw else jfn, x)
    op.__name__ = name
    return op


exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
abs = _unary("abs", jnp.abs)
absolute = abs
sign = _unary("sign", jnp.sign)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
frac = _unary("frac", lambda a: a - jnp.trunc(a))
square = _unary("square", jnp.square)
reciprocal = _unary("reciprocal", lambda a: 1.0 / a)
neg = _unary("neg", jnp.negative)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
i0 = _unary("i0", jax.scipy.special.i0)
i0e = _unary("i0e", jax.scipy.special.i0e)
i1 = _unary("i1", jax.scipy.special.i1)
i1e = _unary("i1e", jax.scipy.special.i1e)
sigmoid = _unary("sigmoid", jax.nn.sigmoid)
logit = _unary("logit", lambda a: jnp.log(a / (1 - a)))
conj = _unary("conj", jnp.conj)
angle = _unary("angle", jnp.angle)
real = _unary("real", jnp.real)
imag = _unary("imag", jnp.imag)
deg2rad = _unary("deg2rad", jnp.deg2rad)
rad2deg = _unary("rad2deg", jnp.rad2deg)
exponent = None  # placeholder removed below


def rsqrt_(x):  # common inplace variants are installed in __init__
    return rsqrt(x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op("nan_to_num",
                    lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


def clip(x, min=None, max=None, name=None):
    lo = unwrap(min) if min is not None else None
    hi = unwrap(max) if max is not None else None
    return apply_op("clip", lambda a: jnp.clip(a, lo, hi), x)


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        return apply_op("lerp", lambda a, b: a + weight * (b - a), x, y)
    return apply_op("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)


def isnan(x, name=None):
    return Tensor(jnp.isnan(unwrap(x)))


def isinf(x, name=None):
    return Tensor(jnp.isinf(unwrap(x)))


def isfinite(x, name=None):
    return Tensor(jnp.isfinite(unwrap(x)))


def isneginf(x, name=None):
    return Tensor(jnp.isneginf(unwrap(x)))


def isposinf(x, name=None):
    return Tensor(jnp.isposinf(unwrap(x)))


def isreal(x, name=None):
    return Tensor(jnp.isreal(unwrap(x)))


# ---- elementwise binary ------------------------------------------------------
def _binary(name, jfn):
    def op(x, y, name_=None):
        return apply_op(name, jfn, x, y)
    op.__name__ = name
    return op


add = _binary("add", jnp.add)
subtract = _binary("subtract", jnp.subtract)
multiply = _binary("multiply", jnp.multiply)
divide = _binary("divide", jnp.divide)
floor_divide = _binary("floor_divide", jnp.floor_divide)
mod = _binary("mod", jnp.mod)
remainder = mod
floor_mod = mod
fmod = _binary("fmod", jnp.fmod)
maximum = _binary("maximum", jnp.maximum)
minimum = _binary("minimum", jnp.minimum)
fmax = _binary("fmax", jnp.fmax)
fmin = _binary("fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)
hypot = _binary("hypot", jnp.hypot)
logaddexp = _binary("logaddexp", jnp.logaddexp)
nextafter = _binary("nextafter", jnp.nextafter)
copysign = _binary("copysign", jnp.copysign)
heaviside = _binary("heaviside", jnp.heaviside)
ldexp = _binary("ldexp", jnp.ldexp)
gcd = _binary("gcd", jnp.gcd)
lcm = _binary("lcm", jnp.lcm)
inner = _binary("inner", jnp.inner)


def pow(x, y, name=None):
    if isinstance(y, (int, float)):
        return apply_op("pow", lambda a: jnp.power(a, y), x)
    return apply_op("pow", jnp.power, x, y)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = unwrap(scale), unwrap(bias)
    def f(a):
        out = a * jnp.asarray(s, a.dtype) + jnp.asarray(b, a.dtype) if bias_after_scale \
            else (a + jnp.asarray(b, a.dtype)) * jnp.asarray(s, a.dtype)
        return out
    return apply_op("scale", f, x)


def multiplex(inputs, index, name=None):
    def f(idx, *ins):
        stacked = jnp.stack(ins, axis=0)
        return jnp.take_along_axis(stacked, idx.reshape(1, -1, *([1] * (stacked.ndim - 2))), axis=0)[0]
    return apply_op("multiplex", lambda *ins: f(unwrap(index).reshape(-1), *ins), *inputs)


# ---- reductions --------------------------------------------------------------
def _norm_axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        ax = np.asarray(axis._data)
        return tuple(int(a) for a in np.atleast_1d(ax))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    dt = dtypes.convert_dtype(dtype)
    def f(a):
        if dt is None and np.dtype(a.dtype) in (np.dtype(np.int32), np.dtype(np.bool_)):
            return jnp.sum(a, axis=ax, keepdims=keepdim,
                           dtype=dtypes.convert_dtype(np.int64))
        return jnp.sum(a, axis=ax, keepdims=keepdim, dtype=dt)
    return apply_op("sum", f, x)


def mean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("mean", lambda a: jnp.mean(a, axis=ax, keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    ax = _norm_axis(axis)
    dt = dtypes.convert_dtype(dtype)
    return apply_op("prod", lambda a: jnp.prod(a, axis=ax, keepdims=keepdim, dtype=dt), x)


def max(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("max", lambda a: jnp.max(a, axis=ax, keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("min", lambda a: jnp.min(a, axis=ax, keepdims=keepdim), x)


def amax(x, axis=None, keepdim=False, name=None):
    return max(x, axis, keepdim)


def amin(x, axis=None, keepdim=False, name=None):
    return min(x, axis, keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("logsumexp", lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype)
    def f(a):
        if axis is None:
            return jnp.cumsum(a.reshape(-1), dtype=dt)
        return jnp.cumsum(a, axis=int(axis), dtype=dt)
    return apply_op("cumsum", f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    dt = dtypes.convert_dtype(dtype)
    return apply_op("cumprod", lambda a: jnp.cumprod(a, axis=dim, dtype=dt), x)


def cummax(x, axis=None, dtype="int64", name=None):
    def f(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else int(axis)
        vals = jax.lax.associative_scan(jnp.maximum, arr, axis=ax)
        n = arr.shape[ax]
        ind_shape = [1] * arr.ndim
        ind_shape[ax] = n
        idx = jnp.arange(n).reshape(ind_shape)
        idx = jnp.broadcast_to(idx, arr.shape)
        def mx(c, x_):
            cv, ci = c
            xv, xi = x_
            take_x = xv >= cv
            return jnp.where(take_x, xv, cv), jnp.where(take_x, xi, ci)
        _, inds = jax.lax.associative_scan(lambda c, x_: mx(c, x_), (arr, idx), axis=ax)
        return vals, inds.astype(dtypes.convert_dtype(dtype))
    out = apply_op("cummax", f, x)
    return out


def cummin(x, axis=None, dtype="int64", name=None):
    def f(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else int(axis)
        vals = jax.lax.associative_scan(jnp.minimum, arr, axis=ax)
        n = arr.shape[ax]
        ind_shape = [1] * arr.ndim
        ind_shape[ax] = n
        idx = jnp.broadcast_to(jnp.arange(n).reshape(ind_shape), arr.shape)
        def mn(c, x_):
            cv, ci = c
            xv, xi = x_
            take_x = xv <= cv
            return jnp.where(take_x, xv, cv), jnp.where(take_x, xi, ci)
        _, inds = jax.lax.associative_scan(mn, (arr, idx), axis=ax)
        return vals, inds.astype(dtypes.convert_dtype(dtype))
    return apply_op("cummin", f, x)


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        arr = a.reshape(-1) if axis is None else a
        ax = 0 if axis is None else int(axis)
        return jax.lax.associative_scan(jnp.logaddexp, arr, axis=ax)
    return apply_op("logcumsumexp", f, x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    dt = dtypes.convert_dtype(dtype)
    return apply_op("nansum", lambda a: jnp.nansum(a, axis=ax, keepdims=keepdim, dtype=dt), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return apply_op("nanmean", lambda a: jnp.nanmean(a, axis=ax, keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = _norm_axis(axis)
    return Tensor(jnp.count_nonzero(unwrap(x), axis=ax, keepdims=keepdim))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def add_n(inputs, name=None):
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    def f(*arrs):
        out = arrs[0]
        for a in arrs[1:]:
            out = out + a
        return out
    return apply_op("add_n", f, *ins)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op("addmm", lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def outer(x, y, name=None):
    return apply_op("outer", lambda a, b: jnp.outer(a, b), x, y)


def kron(x, y, name=None):
    return apply_op("kron", jnp.kron, x, y)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), x)


def dot(x, y, name=None):
    def f(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1)
    return apply_op("dot", f, x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return apply_op("matmul", f, x, y)


def mm(x, y, name=None):
    return matmul(x, y)


def bmm(x, y, name=None):
    return apply_op("bmm", jnp.matmul, x, y)


def mv(x, vec, name=None):
    return apply_op("mv", jnp.matmul, x, vec)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


def softplus_op(x, beta=1, threshold=20):
    return apply_op("softplus", lambda a: jax.nn.softplus(a * beta) / beta, x)


def increment(x, value=1.0, name=None):
    x._data = unwrap(x) + jnp.asarray(value, x.dtype)
    return x


def all_finite(tensors):
    arrs = [unwrap(t).astype(jnp.float32) for t in tensors]
    ok = jnp.asarray(True)
    for a in arrs:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a)))
    return Tensor(ok)
