"""Custom C++ op extension (reference: python/paddle/utils/cpp_extension —
load()/CppExtension compiling user C++ into ops registered with autograd).

TPU-native framing: device math belongs in Pallas/XLA, so custom C++ ops are
HOST ops — compiled with the same lazy g++ builder as the native runtime and
executed under jit via jax.pure_callback (XLA's host-callback mechanism,
the custom-call analog). Declared gradients hook into the tape via
jax.custom_vjp, so custom ops compose with autograd and to_static capture.

User ABI (elementwise/same-shape family, f32):
    extern "C" void <op>(const float* x, int64_t n, float* out);
    extern "C" void <op>_grad(const float* x, const float* gout,
                              int64_t n, float* gx);        // optional
load() introspects the .so and exposes one Python op per symbol.
"""
from __future__ import annotations

import ctypes
import os

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.native.build import load as _build_load

__all__ = ["load", "CppExtension", "CUDAExtension", "BuildExtension", "setup"]


def _list_symbols(so_path):
    """Exported function names via `nm -D` (dynamic symbol table)."""
    import subprocess
    try:
        r = subprocess.run(["nm", "-D", "--defined-only", so_path],
                           capture_output=True, text=True, timeout=30)
    except OSError:
        return []
    out = []
    for line in r.stdout.splitlines():
        parts = line.split()
        if len(parts) >= 3 and parts[1] in ("T", "t"):
            out.append(parts[2])
    return out


class _CustomOp:
    def __init__(self, name, fn, grad_fn=None):
        self._name = name
        self._fn = fn
        self._grad_fn = grad_fn
        fn.argtypes = [ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                       ctypes.POINTER(ctypes.c_float)]
        fn.restype = None
        if grad_fn is not None:
            grad_fn.argtypes = [ctypes.POINTER(ctypes.c_float),
                                ctypes.POINTER(ctypes.c_float),
                                ctypes.c_int64,
                                ctypes.POINTER(ctypes.c_float)]
            grad_fn.restype = None
        self._jax_fn = self._make_jax_fn()

    def _host_fwd(self, x):
        a = np.ascontiguousarray(x, np.float32)
        out = np.empty_like(a)
        self._fn(a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), a.size,
                 out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out

    def _host_bwd(self, x, g):
        a = np.ascontiguousarray(x, np.float32)
        go = np.ascontiguousarray(g, np.float32)
        gx = np.empty_like(a)
        self._grad_fn(a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      go.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                      a.size,
                      gx.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return gx

    def _make_jax_fn(self):
        def call(x):
            # concrete arrays run the C++ directly on host (works on every
            # backend, incl. PJRT plugins without host-callback support);
            # tracers (jit/to_static) lower to an XLA host callback
            if not isinstance(x, jax.core.Tracer):
                return jnp.asarray(self._host_fwd(np.asarray(x)))
            shape = jax.ShapeDtypeStruct(jnp.shape(x), jnp.float32)
            return jax.pure_callback(self._host_fwd, shape,
                                     x.astype(jnp.float32), vmap_method=None)

        if self._grad_fn is None:
            return call

        @jax.custom_vjp
        def op(x):
            return call(x)

        def fwd(x):
            return call(x), x

        def bwd(x, g):
            if not (isinstance(x, jax.core.Tracer) or
                    isinstance(g, jax.core.Tracer)):
                return (jnp.asarray(self._host_bwd(np.asarray(x),
                                                   np.asarray(g))),)
            shape = jax.ShapeDtypeStruct(jnp.shape(x), jnp.float32)
            gx = jax.pure_callback(self._host_bwd, shape,
                                   x.astype(jnp.float32),
                                   g.astype(jnp.float32), vmap_method=None)
            return (gx,)

        op.defvjp(fwd, bwd)
        return op

    def __call__(self, x):
        return apply_op(f"custom_{self._name}", self._jax_fn, x)


class _ExtensionModule:
    def __init__(self, name, ops):
        self.__name__ = name
        for op_name, op in ops.items():
            setattr(self, op_name, op)
        self._ops = ops

    def op_names(self):
        return sorted(self._ops)


def load(name, sources, extra_cxx_cflags=None, verbose=False, **kw):
    """Compile user sources into custom ops (reference:
    cpp_extension.py:895 load — JIT compile + import)."""
    if isinstance(sources, str):
        sources = [sources]
    if len(sources) != 1:
        # multiple translation units: concatenate? keep contract simple
        raise ValueError("load() takes exactly one source file here; "
                         "#include shared code from it")
    src = os.path.abspath(sources[0])
    lib = _build_load(f"ext_{name}", src,
                      extra_flags=tuple(extra_cxx_cflags or ()))
    if lib is None:
        from ...core.native.build import last_error
        raise RuntimeError(
            f"cpp_extension: failed to compile {src}:\n"
            f"{last_error(f'ext_{name}') or '(no compiler diagnostic)'}")
    so_path = lib._name
    syms = [s for s in _list_symbols(so_path) if not s.startswith("_")]
    ops = {}
    for s in syms:
        if s.endswith("_grad"):
            continue
        grad = getattr(lib, s + "_grad", None) if s + "_grad" in syms else None
        ops[s] = _CustomOp(s, getattr(lib, s), grad)
    if not ops:
        raise RuntimeError(f"cpp_extension: no extern \"C\" ops exported "
                           f"from {src}")
    return _ExtensionModule(name, ops)


# setuptools-style surface (reference cpp_extension.setup/CppExtension);
# the JIT `load` above is the supported path on this backend.
class CppExtension:
    def __init__(self, sources, **kw):
        self.sources = sources
        self.kw = kw


CUDAExtension = CppExtension


class BuildExtension:
    @staticmethod
    def with_options(**kw):
        return BuildExtension


def setup(**kw):
    raise NotImplementedError(
        "cpp_extension.setup: use cpp_extension.load(name, sources) — the "
        "JIT path — on this backend")
