"""paddle.utils (reference: python/paddle/utils/)."""
from __future__ import annotations

import importlib
import warnings

from . import unique_name  # noqa: F401


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"module {module_name} is required") from None


def deprecated(update_to="", since="", reason="", level=0):
    def decorator(func):
        def wrapper(*args, **kwargs):
            warnings.warn(f"{func.__name__} is deprecated since {since}. {reason} "
                          f"Use {update_to} instead.", DeprecationWarning)
            return func(*args, **kwargs)
        return wrapper
    return decorator


def run_check():
    """paddle.utils.run_check — sanity-check install + device."""
    import jax
    import numpy as np
    from .. import ops
    a = ops.ones([2, 2])
    b = (a @ a).numpy()
    assert np.allclose(b, 2 * np.ones((2, 2)))
    devs = jax.devices()
    print(f"paddle_tpu is installed successfully! "  # graftlint: disable=no-adhoc-telemetry
          f"devices: {devs}")


def flops(net, input_size, custom_ops=None, print_detail=False):
    from ..hapi.model import flops as _flops
    return _flops(net, input_size, custom_ops, print_detail)


def require_version(min_version, max_version=None):
    """reference utils/install_check-style version gate against this build's
    version string."""
    from ..version import __version__

    def _key(v):
        parts = [int(p) if p.isdigit() else 0 for p in str(v).split(".")[:3]]
        return tuple(parts + [0] * (3 - len(parts)))   # zero-pad: 0.1 == 0.1.0
    cur = _key(__version__)
    if _key(min_version) > cur:
        raise Exception(
            f"paddle_tpu>={min_version} required, found {__version__}")
    if max_version is not None and _key(max_version) < cur:
        raise Exception(
            f"paddle_tpu<={max_version} required, found {__version__}")
