"""nn.utils (reference: python/paddle/nn/utils/)."""
from ...core.tensor import Tensor
import jax.numpy as jnp


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate([p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p._data = vec._data[offset:offset + n].reshape(p._data.shape).astype(p._data.dtype)
        offset += n
