"""Weight initializers (reference: python/paddle/nn/initializer/)."""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtype as dtypes
from ..core.rng import next_key
from ..core.tensor import Tensor


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(tuple(shape), self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        out = jax.random.normal(next_key(), tuple(shape), jnp.float32)
        return (out * self.std + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        out = jax.random.truncated_normal(next_key(), self.a, self.b, tuple(shape), jnp.float32)
        return (out * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        out = jax.random.uniform(next_key(), tuple(shape), jnp.float32,
                                 minval=self.low, maxval=self.high)
        return out.astype(dtype)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # paddle convention: fan_in = shape[0]*rf for conv (NCHW weight OIHW),
    # for 2D [in, out] linear weights fan_in = shape[0]
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(next_key(), tuple(shape), jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self._fan_in, self._fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        fo = self._fan_out if self._fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fi)
        return (jax.random.normal(next_key(), tuple(shape), jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self._fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self._fan_in if self._fan_in is not None else fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        v = self.value._data if isinstance(self.value, Tensor) else jnp.asarray(np.asarray(self.value))
        return v.astype(dtype).reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        out = jax.nn.initializers.orthogonal(scale=self.gain)(next_key(), tuple(shape), jnp.float32)
        return out.astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        arr = np.zeros(tuple(shape), np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = [s // 2 for s in shape[2:]]
        for g in range(self.groups):
            for i in range(mins):
                arr[(g * (oc // self.groups) + i, i) + tuple(centers)] = 1.0
        return jnp.asarray(arr).astype(dtype)


# paddle.ParamAttr analog
class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0, regularizer=None,
                 trainable=True, do_model_average=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def _resolve(attr, default_init):
    """Normalize a ParamAttr/Initializer/bool/None into (ParamAttr, Initializer)."""
    if attr is False:
        return None, None
    if attr is None:
        return ParamAttr(), default_init
    if isinstance(attr, Initializer):
        return ParamAttr(initializer=attr), attr
    if isinstance(attr, ParamAttr):
        return attr, attr.initializer or default_init
    if isinstance(attr, str):
        return ParamAttr(name=attr), default_init
    raise TypeError(f"bad param attr {attr!r}")
