"""paddle.nn surface (reference: python/paddle/nn/__init__.py)."""
from .layer import *  # noqa: F401,F403
from .layer.layers import Layer  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .initializer import ParamAttr  # noqa: F401
from .clip import (ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,  # noqa: F401
                   clip_grad_norm_, clip_grad_value_)
from . import utils  # noqa: F401
