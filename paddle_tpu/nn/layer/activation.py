"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F
from ..initializer import Constant


def _mk(name, fn, **defaults):
    def __init__(self, name_=None, **kw):
        Layer.__init__(self)
        self._kw = {**defaults, **kw}

    def forward(self, x):
        return fn(x, **self._kw)

    return type(name, (Layer,), {"__init__": __init__, "forward": forward})


ReLU = _mk("ReLU", F.relu)
ReLU6 = _mk("ReLU6", F.relu6)
Sigmoid = _mk("Sigmoid", F.sigmoid)
Tanh = _mk("Tanh", F.tanh)
Silu = _mk("Silu", F.silu)
Swish = _mk("Swish", F.silu)
Mish = _mk("Mish", F.mish)
Softsign = _mk("Softsign", F.softsign)
Tanhshrink = _mk("Tanhshrink", F.tanhshrink)
LogSigmoid = _mk("LogSigmoid", F.log_sigmoid)
Hardswish = _mk("Hardswish", F.hardswish)
SELU = _mk("SELU", F.selu)


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self._approximate = approximate

    def forward(self, x):
        return F.gelu(x, approximate=self._approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.elu(x, self._alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self._alpha = alpha

    def forward(self, x):
        return F.celu(x, self._alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):
        super().__init__()
        self._min, self._max = min, max

    def forward(self, x):
        return F.hardtanh(x, self._min, self._max)


class Hardsigmoid(Layer):
    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        return F.hardsigmoid(x)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._t = threshold

    def forward(self, x):
        return F.hardshrink(x, self._t)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self._t = threshold

    def forward(self, x):
        return F.softshrink(x, self._t)


class Softplus(Layer):
    def __init__(self, beta=1, threshold=20, name=None):
        super().__init__()
        self._beta, self._threshold = beta, threshold

    def forward(self, x):
        return F.softplus(x, self._beta, self._threshold)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self._axis)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr,
                                            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=0.125, upper=0.3333333, name=None):
        super().__init__()
        self._lower, self._upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self._lower, self._upper, training=self.training)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self._threshold, self._value = threshold, value

    def forward(self, x):
        return F.thresholded_relu(x, self._threshold, self._value)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self._groups, self._axis = groups, axis

    def forward(self, x):
        return F.maxout(x, self._groups, self._axis)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, self._axis)
