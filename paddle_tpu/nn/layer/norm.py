"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .layers import Layer
from .. import functional as F
from ..initializer import Constant
from ...core.tensor import Tensor


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._normalized_shape = ([normalized_shape] if isinstance(normalized_shape, int)
                                  else list(normalized_shape))
        self._epsilon = epsilon
        self.weight = self.create_parameter(self._normalized_shape, attr=weight_attr,
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr,
                                          is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """rms_norm is a first-class op in the reference (phi/kernels/rms_norm_kernel.h)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._hidden_size = hidden_size if isinstance(hidden_size, int) else hidden_size[-1]
        self._epsilon = epsilon
        self.weight = self.create_parameter([self._hidden_size], attr=weight_attr,
                                            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self._epsilon)

    def extra_repr(self):
        return f"hidden_size={self._hidden_size}, epsilon={self._epsilon}"


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter([num_features], attr=weight_attr,
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros(num_features, jnp.float32),
                                             persistable=True))
        self.register_buffer("_variance", Tensor(jnp.ones(num_features, jnp.float32),
                                                 persistable=True))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format in ("NCL", "NC") else "NHWC",
                         use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else "NHWC", use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """On TPU, batch stats sync happens via GSPMD (stats computed over the global
    batch when the batch axis is sharded under jit) — the layer is the same.
    Reference: python/paddle/nn/layer/norm.py SyncBatchNorm (NCCL allreduce)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            out.weight, out.bias = layer.weight, layer.bias
            out._mean, out._variance = layer._mean, layer._variance
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups, self._num_channels = num_groups, num_channels
        self._epsilon, self._data_format = epsilon, data_format
        self.weight = self.create_parameter([num_channels], attr=weight_attr,
                                            default_initializer=Constant(1.0))
        self.bias = self.create_parameter([num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias,
                            self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon, self._data_format = epsilon, data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter([num_features], attr=weight_attr,
                                                default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """W / sigma(W) via power iteration (reference: nn/layer/norm.py
    SpectralNorm:1272; phi spectral_norm kernel). forward(weight) -> weight
    normalized by its leading singular value; u/v persist as buffers and the
    power iterations run under stop_gradient (matching the reference kernel,
    which treats u/v as constants in the backward)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = epsilon
        self._shape = list(weight_shape)
        h = int(weight_shape[dim])
        w = int(np.prod(weight_shape)) // h
        from ...core.rng import next_key
        import jax
        ku, kv = jax.random.split(next_key())
        dt = jnp.dtype(dtype)
        u = jax.random.normal(ku, (h,), jnp.float32)
        v = jax.random.normal(kv, (w,), jnp.float32)
        self.register_buffer("weight_u",
                             Tensor((u / jnp.linalg.norm(u)).astype(dt)))
        self.register_buffer("weight_v",
                             Tensor((v / jnp.linalg.norm(v)).astype(dt)))

    def forward(self, x):
        from ...core.dispatch import apply_op, unwrap
        import jax
        dim, iters, eps = self._dim, self._power_iters, self._eps

        def f(w0, u0, v0):
            perm = [dim] + [i for i in range(w0.ndim) if i != dim]
            # iterate in f32 for stability; return in the weight's dtype
            m = jnp.transpose(w0, perm).reshape(w0.shape[dim], -1) \
                .astype(jnp.float32)
            u, v = u0.astype(jnp.float32), v0.astype(jnp.float32)

            def body(i, uv):
                u, v = uv
                v = m.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = m @ v
                u = u / (jnp.linalg.norm(u) + eps)
                return (u, v)
            u, v = jax.lax.fori_loop(0, iters, body, (u, v))
            u, v = jax.lax.stop_gradient(u), jax.lax.stop_gradient(v)
            sigma = (u @ (m @ v)).astype(w0.dtype)
            return w0 / sigma, u.astype(u0.dtype), v.astype(v0.dtype)

        out, u2, v2 = apply_op("spectral_norm", f, x, self.weight_u,
                               self.weight_v)
        self.weight_u._data = unwrap(u2)
        self.weight_v._data = unwrap(v2)
        return out
