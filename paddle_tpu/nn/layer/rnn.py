"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py — SimpleRNNCell,
LSTMCell, GRUCell, RNN wrapper, SimpleRNN/LSTM/GRU multi-layer nets).

TPU-native: the per-step cell math is a pure-jnp function; a full sequence
runs as ONE dispatched op whose body is jax.lax.scan over time — XLA compiles
the recurrence into a single fused loop (no per-step Python dispatch, static
shapes, grad via scan's linearization)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op
from ...core.tensor import Tensor
from ..initializer import Uniform as UniformInit
from .layers import Layer

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


def _std_init(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return UniformInit(-k, k)


class RNNCellBase(Layer):
    def get_initial_states(self, batch, hidden_size=None, dtype="float32"):
        h = hidden_size or self.hidden_size
        return Tensor(jnp.zeros((batch, h), dtype))


class SimpleRNNCell(RNNCellBase):
    """h' = act(x W_ih^T + b_ih + h W_hh^T + b_hh) (reference SimpleRNNCell)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               attr=weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               attr=weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], attr=bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], attr=bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)

    def _step(self, x, h, wih, whh, bih, bhh):
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        return act(x @ wih.T + bih + h @ whh.T + bhh)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0])
        out = apply_op("simple_rnn_cell", self._step, inputs, states,
                       self.weight_ih, self.weight_hh, self.bias_ih,
                       self.bias_hh)
        return out, out

    @property
    def state_shape(self):
        return (self.hidden_size,)


class LSTMCell(RNNCellBase):
    """Gates i,f,g,o packed in [4H, ...] rows (reference LSTMCell)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               attr=weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               attr=weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size],
                                             attr=bias_ih_attr, is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size],
                                             attr=bias_hh_attr, is_bias=True,
                                             default_initializer=init)

    @staticmethod
    def _step(x, h, c, wih, whh, bih, bhh):
        gates = x @ wih.T + bih + h @ whh.T + bhh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c2 = f * c + i * g
        return jnp.tanh(c2) * o, c2

    def forward(self, inputs, states=None):
        if states is None:
            b = inputs.shape[0]
            states = (self.get_initial_states(b), self.get_initial_states(b))
        h, c = states
        h2, c2 = apply_op("lstm_cell", self._step, inputs, h, c,
                          self.weight_ih, self.weight_hh, self.bias_ih,
                          self.bias_hh)
        return h2, (h2, c2)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    """Gates r,z,c packed in [3H, ...] rows (reference GRUCell)."""

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        init = _std_init(hidden_size)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               attr=weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               attr=weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size],
                                             attr=bias_ih_attr, is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size],
                                             attr=bias_hh_attr, is_bias=True,
                                             default_initializer=init)

    @staticmethod
    def _step(x, h, wih, whh, bih, bhh):
        xg = x @ wih.T + bih
        hg = h @ whh.T + bhh
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        return (1 - z) * c + z * h

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0])
        h2 = apply_op("gru_cell", self._step, inputs, states,
                      self.weight_ih, self.weight_hh, self.bias_ih,
                      self.bias_hh)
        return h2, h2

    @property
    def state_shape(self):
        return (self.hidden_size,)


# ---- sequence runners (lax.scan inside one dispatched op) --------------------
def _scan_layer(mode, x, h0, c0, wih, whh, bih, bhh, reverse=False):
    """x [B, T, I] → (out [B, T, H], hT, cT). Pure-jnp; called under vjp."""
    xs = jnp.swapaxes(x, 0, 1)                       # [T, B, I]
    if reverse:
        xs = xs[::-1]

    if mode == "LSTM":
        def body(carry, xt):
            h, c = carry
            h2, c2 = LSTMCell._step(xt, h, c, wih, whh, bih, bhh)
            return (h2, c2), h2
        (hT, cT), ys = jax.lax.scan(body, (h0, c0), xs)
    elif mode == "GRU":
        def body(h, xt):
            h2 = GRUCell._step(xt, h, wih, whh, bih, bhh)
            return h2, h2
        hT, ys = jax.lax.scan(body, h0, xs)
        cT = hT
    else:
        act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

        def body(h, xt):
            h2 = act(xt @ wih.T + bih + h @ whh.T + bhh)
            return h2, h2
        hT, ys = jax.lax.scan(body, h0, xs)
        cT = hT
    if reverse:
        ys = ys[::-1]
    return jnp.swapaxes(ys, 0, 1), hT, cT


class _MultiLayerRNN(Layer):
    """Shared driver for SimpleRNN/LSTM/GRU (reference RNNBase)."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        if direction not in ("forward", "bidirect", "bidirectional"):
            raise ValueError(f"bad direction {direction}")
        self.mode = mode
        self.input_size, self.hidden_size = input_size, hidden_size
        self.num_layers = num_layers
        self.bidirectional = direction != "forward"
        self.num_directions = 2 if self.bidirectional else 1
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        gate_mul = {"LSTM": 4, "GRU": 3}.get(mode, 1)
        init = _std_init(hidden_size)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 else \
                    hidden_size * self.num_directions
                sfx = f"l{layer}" + ("_reverse" if d else "")
                setattr(self, f"weight_ih_{sfx}", self.create_parameter(
                    [gate_mul * hidden_size, in_sz], attr=weight_ih_attr,
                    default_initializer=init))
                setattr(self, f"weight_hh_{sfx}", self.create_parameter(
                    [gate_mul * hidden_size, hidden_size],
                    attr=weight_hh_attr, default_initializer=init))
                setattr(self, f"bias_ih_{sfx}", self.create_parameter(
                    [gate_mul * hidden_size], attr=bias_ih_attr, is_bias=True,
                    default_initializer=init))
                setattr(self, f"bias_hh_{sfx}", self.create_parameter(
                    [gate_mul * hidden_size], attr=bias_hh_attr, is_bias=True,
                    default_initializer=init))

    def _mode_key(self):
        if self.mode == "RNN":
            return "RNN_TANH" if self.activation == "tanh" else "RNN_RELU"
        return self.mode

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs
        if self.time_major:
            from ... import ops
            x = ops.transpose(x, [1, 0, 2])
        B = x.shape[0]
        L, D, H = self.num_layers, self.num_directions, self.hidden_size
        is_lstm = self.mode == "LSTM"
        if initial_states is None:
            z = Tensor(jnp.zeros((L * D, B, H), jnp.float32))
            h0_all, c0_all = (z, z) if is_lstm else (z, None)
        else:
            h0_all, c0_all = initial_states if is_lstm else (initial_states,
                                                             None)
        mode = self._mode_key()
        h_outs, c_outs = [], []
        for layer in range(L):
            outs = []
            for d in range(D):
                sfx = f"l{layer}" + ("_reverse" if d else "")
                wih = getattr(self, f"weight_ih_{sfx}")
                whh = getattr(self, f"weight_hh_{sfx}")
                bih = getattr(self, f"bias_ih_{sfx}")
                bhh = getattr(self, f"bias_hh_{sfx}")
                idx = layer * D + d
                h0 = h0_all[idx]
                c0 = c0_all[idx] if is_lstm else h0

                def seq_fn(xx, hh, cc, a, b, e, g, _d=d, _mode=mode):
                    return _scan_layer(_mode, xx, hh, cc, a, b, e, g,
                                       reverse=bool(_d))

                out, hT, cT = apply_op(f"{mode.lower()}_layer", seq_fn, x, h0,
                                       c0, wih, whh, bih, bhh)
                outs.append(out)
                h_outs.append(hT)
                c_outs.append(cT)
            if D == 2:
                from ... import ops
                x = ops.concat(outs, axis=-1)
            else:
                x = outs[0]
            if self.dropout and layer < L - 1 and self.training:
                from .. import functional as F
                x = F.dropout(x, p=self.dropout)
        from ... import ops
        h_stack = ops.stack(h_outs, axis=0)
        out = ops.transpose(x, [1, 0, 2]) if self.time_major else x
        if is_lstm:
            return out, (h_stack, ops.stack(c_outs, axis=0))
        return out, h_stack


class SimpleRNN(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kw):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kw)


class LSTM(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        kw.pop("activation", None)
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class GRU(_MultiLayerRNN):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kw):
        kw.pop("activation", None)
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kw)


class RNN(Layer):
    """Generic cell runner (reference rnn.py RNN): steps a cell over time via
    a Python loop at the Tensor level — works with ANY user cell."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        x = inputs if not self.time_major else ops.transpose(inputs, [1, 0, 2])
        T = x.shape[1]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        state = initial_states
        outs = [None] * T
        for t in steps:
            out, state = self.cell(x[:, t], state)
            outs[t] = out
        y = ops.stack(outs, axis=1)
        if self.time_major:
            y = ops.transpose(y, [1, 0, 2])
        return y, state


class BiRNN(Layer):
    """Forward + backward cells, concatenated outputs (reference BiRNN)."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        y_fw, st_fw = self.rnn_fw(inputs, s_fw)
        y_bw, st_bw = self.rnn_bw(inputs, s_bw)
        return ops.concat([y_fw, y_bw], axis=-1), (st_fw, st_bw)
