"""nn.Layer base (reference: python/paddle/nn/layer/layers.py:354).

Parameters/buffers/sublayers registries with __setattr__ magic, hooks, state_dict,
train/eval, dtype movement. Parameters are plain Tensors (mutable `_data`), so a
Layer works both eagerly and under program capture.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Iterator, Optional

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, Parameter
from ...core import dtype as dtypes
from ..initializer import (Initializer, XavierUniform, Constant, ParamAttr, _resolve,
                           Uniform)
import math


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks, self._id = hooks, hook_id

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = OrderedDict()
        self._forward_post_hooks = OrderedDict()
        self._hook_id = 0
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype)
        self._name_scope = name_scope or self.__class__.__name__.lower()

    # ---- attribute magic -----------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter) and params is not None:
            params[name] = value
            self.__dict__.pop(name, None)
            return
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Layer) and layers is not None:
            layers[name] = value
            self.__dict__.pop(name, None)
            return
        bufs = self.__dict__.get("_buffers")
        if bufs is not None and name in bufs:
            bufs[name] = value
            return
        if params is not None and name in params:
            if value is None:
                del params[name]
            else:
                params[name] = value
            return
        if layers is not None and name in layers:
            if value is None:
                del layers[name]
            else:
                layers[name] = value
            return
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ---- parameter creation --------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) or self._dtype
        if default_initializer is None:
            default_initializer = Constant(0.0) if is_bias else XavierUniform()
        pattr, init = _resolve(attr, default_initializer)
        if pattr is None:
            return None
        data = init(shape, dtype)
        p = Parameter(data, name=pattr.name, trainable=pattr.trainable)
        p.optimize_attr["learning_rate"] = pattr.learning_rate
        p.regularizer = pattr.regularizer
        p.need_clip = pattr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            parameter = Parameter(parameter._data if isinstance(parameter, Tensor)
                                  else jnp.asarray(parameter))
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(jnp.asarray(np.asarray(tensor)))
        if tensor is not None:
            tensor.persistable = persistable
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ---- traversal -----------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                for n, p in layer.named_parameters(sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = f"{prefix}.{lname}" if prefix else lname
                yield from layer.named_buffers(sub_prefix)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(sub_prefix, include_self=True)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items() if l is not None)

    def apply(self, fn):
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ---- modes ---------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    # ---- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call ----------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            mod_str = repr(layer)
            mod_str = "\n".join("  " + l for l in mod_str.split("\n"))
            lines.append(f"  ({name}): {mod_str.strip()}")
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"

    # ---- state dict ----------------------------------------------------------
    def _named_persistable_buffers(self, prefix=""):
        """Like named_buffers but consults each OWNING layer's non-persistable set."""
        for name, b in self._buffers.items():
            if b is not None and name not in self._non_persistable_buffer_names:
                yield (f"{prefix}.{name}" if prefix else name), b
        for lname, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{lname}" if prefix else lname
            yield from layer._named_persistable_buffers(sub_prefix)

    def state_dict(self, destination=None, include_sublayers=True, use_hook=True,
                   structured_name_prefix=""):
        dest = destination if destination is not None else OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            dest[name] = p
        for name, b in self._named_persistable_buffers(structured_name_prefix.rstrip(".")):
            dest[name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, value in state_dict.items():
            if name not in own:
                unexpected.append(name)
                continue
            target = own[name]
            v = value._data if isinstance(value, Tensor) else jnp.asarray(np.asarray(value))
            if tuple(v.shape) != tuple(target._data.shape):
                raise ValueError(
                    f"shape mismatch for {name}: checkpoint {tuple(v.shape)} vs "
                    f"model {tuple(target._data.shape)}")
            target._data = v.astype(target._data.dtype)
        for name in own:
            if name not in state_dict:
                missing.append(name)
        return missing, unexpected

    load_dict = set_state_dict

    # ---- dtype/device movement ----------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = dtypes.convert_dtype(dtype)
            self._dtype = dt
            for p in self.parameters():
                if dtypes.is_floating_point(p.dtype):
                    p._data = p._data.astype(dt)
            for b in self.buffers():
                if b is not None and dtypes.is_floating_point(b.dtype):
                    b._data = b._data.astype(dt)
            for l in self.sublayers(include_self=False):
                l._dtype = dt
        if device is not None:
            import jax
            from ...core.device import _parse
            dev = _parse(device)
            for t in list(self.parameters()) + list(self.buffers()):
                if t is not None:
                    t._data = jax.device_put(t._data, dev)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self, set_to_zero=False):
        for p in self.parameters():
            p.clear_grad(set_to_zero)

    def full_name(self):
        return self._name_scope
