"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F


def _mk_pool(name, fn, extra=()):
    class _P(Layer):
        def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                     return_mask=False, exclusive=True, data_format=None, name_=None):
            super().__init__()
            self._args = dict(kernel_size=kernel_size, stride=stride, padding=padding)
            self._ceil = ceil_mode
            self._df = data_format
            self._return_mask = return_mask

        def forward(self, x):
            kw = dict(self._args)
            kw["ceil_mode"] = self._ceil
            if self._df:
                kw["data_format"] = self._df
            if self._return_mask and name.startswith("Max"):
                kw["return_mask"] = True
            return fn(x, **kw)
    _P.__name__ = name
    return _P


MaxPool1D = _mk_pool("MaxPool1D", F.max_pool1d)
MaxPool2D = _mk_pool("MaxPool2D", F.max_pool2d)
MaxPool3D = _mk_pool("MaxPool3D", F.max_pool3d)
AvgPool1D = _mk_pool("AvgPool1D", F.avg_pool1d)
AvgPool2D = _mk_pool("AvgPool2D", F.avg_pool2d)
AvgPool3D = _mk_pool("AvgPool3D", F.avg_pool3d)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self._os)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self._os, self._df = output_size, data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._os, self._df)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self._os, self._df = output_size, data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self._os, self._df)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self._os)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._os)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._os = output_size

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self._os)


class MaxUnPool1D(Layer):
    """Inverse of MaxPool1D(return_mask=True) (reference: nn/layer/pooling.py
    MaxUnPool1D over the phi unpool kernel)."""

    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride, padding=padding,
                        output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, **self._kw)


class MaxUnPool2D(Layer):
    """Inverse of MaxPool2D(return_mask=True)."""

    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride, padding=padding,
                        output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, **self._kw)
