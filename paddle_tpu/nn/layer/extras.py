"""Round-2 layer-surface completion (reference: python/paddle/nn/layer/ —
loss layers, pooling variants, pads, containers, seq2seq decoding)."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor, Parameter
from .layers import Layer
from .. import functional as F


# ---- loss layers (thin wrappers over the functionals) ------------------------
def _loss_layer(name, fn, *fixed_keys, **defaults):
    class _L(Layer):
        def __init__(self, **kw):
            super().__init__()
            cfg = dict(defaults)
            cfg.update(kw)
            self._cfg = cfg

        def forward(self, *args):
            return fn(*args, **self._cfg)
    _L.__name__ = name
    _L.__qualname__ = name
    return _L


PoissonNLLLoss = _loss_layer("PoissonNLLLoss", F.poisson_nll_loss,
                             log_input=True, full=False, epsilon=1e-8,
                             reduction="mean")
GaussianNLLLoss = _loss_layer("GaussianNLLLoss", F.gaussian_nll_loss,
                              full=False, epsilon=1e-6, reduction="mean")
SoftMarginLoss = _loss_layer("SoftMarginLoss", F.soft_margin_loss,
                             reduction="mean")
MultiLabelSoftMarginLoss = _loss_layer("MultiLabelSoftMarginLoss",
                                       F.multi_label_soft_margin_loss,
                                       weight=None, reduction="mean")
MultiMarginLoss = _loss_layer("MultiMarginLoss", F.multi_margin_loss,
                              p=1, margin=1.0, weight=None, reduction="mean")
TripletMarginWithDistanceLoss = _loss_layer(
    "TripletMarginWithDistanceLoss", F.triplet_margin_with_distance_loss,
    distance_function=None, margin=1.0, swap=False, reduction="mean")


class CTCLoss(Layer):
    """reference loss.py CTCLoss."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    """reference loss.py RNNTLoss."""

    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class HSigmoidLoss(Layer):
    """reference loss.py HSigmoidLoss — owns the internal-node weight table
    ((num_classes - 1) rows for the default complete binary tree)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        # the complete binary tree over num_classes leaves has exactly
        # num_classes - 1 internal nodes (heap ids 1..C-1 -> rows 0..C-2),
        # matching the reference's [num_classes - 1, feature_size] weight
        n_nodes = num_classes - 1
        from ..initializer import XavierUniform, Constant
        self.weight = self.create_parameter(
            [n_nodes, feature_size], attr=weight_attr,
            default_initializer=XavierUniform())
        self.bias = self.create_parameter(
            [n_nodes], attr=bias_attr, is_bias=True,
            default_initializer=Constant(0.0))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               bias=self.bias, path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference loss.py AdaptiveLogSoftmaxWithLoss (torch-style cutoffs +
    div_value tail down-projections)."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if cutoffs != sorted(cutoffs) or cutoffs[-1] >= n_classes:
            raise ValueError("cutoffs must be increasing and < n_classes")
        self.cutoffs = cutoffs + [n_classes]
        self.n_clusters = len(cutoffs)
        from ..initializer import XavierUniform, Constant
        head_out = cutoffs[0] + self.n_clusters
        self.head_weight = self.create_parameter(
            [in_features, head_out], default_initializer=XavierUniform())
        self.head_bias = self.create_parameter(
            [head_out], is_bias=True, default_initializer=Constant(0.0)) \
            if head_bias else None
        for i in range(self.n_clusters):
            hsz = max(1, int(in_features / (div_value ** (i + 1))))
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            proj = self.create_parameter([in_features, hsz],
                                         default_initializer=XavierUniform())
            cls = self.create_parameter([hsz, osz],
                                        default_initializer=XavierUniform())
            self.add_parameter(f"tail_proj_{i}", proj)
            self.add_parameter(f"tail_cls_{i}", cls)

    def forward(self, input, label):
        out, loss = F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self._collect_tails(),
            self.cutoffs, head_bias=self.head_bias)
        return out, loss

    def _collect_tails(self):
        tails = []
        for i in range(self.n_clusters):
            tails.append(self._parameters[f"tail_proj_{i}"])
            tails.append(self._parameters[f"tail_cls_{i}"])
        return tails

    def log_prob(self, input):
        """Full [N, n_classes] log-probabilities (eval utility)."""
        import jax
        x = input
        head = x @ Tensor(self.head_weight._buf)
        if self.head_bias is not None:
            head = head + self.head_bias
        head_lp = F.log_softmax(head, axis=-1)
        parts = [head_lp[:, :self.cutoffs[0]]]
        for i in range(self.n_clusters):
            h = x @ self._parameters[f"tail_proj_{i}"]
            tail_lp = F.log_softmax(h @ self._parameters[f"tail_cls_{i}"],
                                    axis=-1)
            cluster = head_lp[:, self.cutoffs[0] + i].unsqueeze(-1)
            parts.append(tail_lp + cluster)
        from ... import ops
        return ops.concat(parts, axis=-1)

    def predict(self, input):
        from ... import ops
        return ops.argmax(self.log_prob(input), axis=-1)


# ---- misc layers -------------------------------------------------------------
class Softmax2D(Layer):
    """reference activation.py Softmax2D: softmax over the channel dim of
    NCHW."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError("Softmax2D expects 3-D or 4-D input")
        return F.softmax(x, axis=-3)


class Unflatten(Layer):
    """reference common.py Unflatten."""

    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ... import ops
        return ops.unflatten(x, self.axis, self.shape)


class ParameterDict(Layer):
    """reference container.py ParameterDict."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters:
            for k, v in (parameters.items() if isinstance(parameters, dict)
                         else parameters):
                self.add_parameter(str(k), v)

    def __getitem__(self, key):
        return self._parameters[str(key)]

    def __setitem__(self, key, value):
        self.add_parameter(str(key), value)

    def __contains__(self, key):
        return str(key) in self._parameters

    def __len__(self):
        return len(self._parameters)

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        for k, v in (parameters.items() if isinstance(parameters, dict)
                     else parameters):
            self.add_parameter(str(k), v)


class _PadCompat(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format=None, name=None):
        super().__init__()
        self._padding = padding
        self._value = value
        self._df = data_format

    def forward(self, x):
        return F.pad(x, self._padding, mode="constant", value=self._value,
                     data_format=self._df or self._default_df)


class ZeroPad1D(_PadCompat):
    """reference common.py ZeroPad1D (NCL)."""
    _default_df = "NCL"


class ZeroPad3D(_PadCompat):
    """reference common.py ZeroPad3D (NCDHW)."""
    _default_df = "NCDHW"


# ---- pooling variants --------------------------------------------------------
class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self._a = (norm_type, kernel_size, stride, padding, ceil_mode,
                   data_format)

    def forward(self, x):
        n, k, s, p, c, df = self._a
        return F.lp_pool1d(x, n, k, s, p, ceil_mode=c, data_format=df)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self._a = (norm_type, kernel_size, stride, padding, ceil_mode,
                   data_format)

    def forward(self, x):
        n, k, s, p, c, df = self._a
        return F.lp_pool2d(x, n, k, s, p, ceil_mode=c, data_format=df)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._a = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        o, k, u, m = self._a
        return F.fractional_max_pool2d(x, o, k, u, return_mask=m)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self._a = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        o, k, u, m = self._a
        return F.fractional_max_pool3d(x, o, k, u, return_mask=m)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self._kw = dict(kernel_size=kernel_size, stride=stride,
                        padding=padding, output_size=output_size)

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, **self._kw)


# ---- seq2seq decoding --------------------------------------------------------
class Decoder:
    """reference decoder.py Decoder protocol (initialize/step/finalize)."""


class BeamSearchDecoder(Decoder):
    """reference decoder.py BeamSearchDecoder over an RNN cell: expand each
    batch row into `beam_size` hypotheses, step the cell on the flattened
    beam batch, keep the top-k continuations by accumulated log-prob."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def _map_states(fn, states):
        """Apply fn to every Tensor leaf, preserving the cell's own state
        structure (Tensor for GRU/SimpleRNN, tuple for LSTM, nests thereof)."""
        if isinstance(states, (list, tuple)):
            return type(states)(BeamSearchDecoder._map_states(fn, s)
                                for s in states)
        return fn(states)

    @staticmethod
    def _first_leaf(states):
        while isinstance(states, (list, tuple)):
            states = states[0]
        return states

    def initialize(self, initial_cell_states):
        B = self._first_leaf(initial_cell_states).shape[0]
        K = self.beam_size

        def tile(t):
            arr = t._data if isinstance(t, Tensor) else jnp.asarray(t)
            return Tensor(jnp.repeat(arr, K, axis=0))
        states = self._map_states(tile, initial_cell_states)
        tokens = Tensor(jnp.full((B * K,), self.start_token, jnp.int32))
        # first expansion: only beam 0 is live so duplicates don't win top-k
        log_probs = jnp.tile(jnp.where(jnp.arange(K) == 0, 0.0, -1e9), B)
        finished = jnp.zeros((B * K,), bool)
        return tokens, states, (Tensor(log_probs), Tensor(finished))

    def step(self, time, inputs, states, aux):
        import jax
        log_probs, finished = aux
        K = self.beam_size
        emb = self.embedding_fn(inputs) if self.embedding_fn else inputs
        out, new_states = self.cell(emb, states)
        logits = self.output_fn(out) if self.output_fn else out
        lp = jax.nn.log_softmax(logits._data.astype(jnp.float32), axis=-1)
        V = lp.shape[-1]
        BK = lp.shape[0]
        B = BK // K
        fin = finished._data
        # finished beams only extend with end_token at prob 1
        keep_end = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        lp = jnp.where(fin[:, None], keep_end[None, :], lp)
        total = log_probs._data[:, None] + lp                     # [BK, V]
        flat = total.reshape(B, K * V)
        top_lp, top_idx = jax.lax.top_k(flat, K)                  # [B, K]
        parent = top_idx // V                                      # beam index
        token = top_idx % V
        flat_parent = (jnp.arange(B)[:, None] * K + parent).reshape(-1)

        def sel(t):
            arr = t._data if isinstance(t, Tensor) else t
            return Tensor(arr[flat_parent])
        new_states = self._map_states(sel, new_states)
        tokens = Tensor(token.reshape(-1).astype(jnp.int32))
        new_fin = fin[flat_parent] | (token.reshape(-1) == self.end_token)
        return (tokens, new_states,
                (Tensor(top_lp.reshape(-1)), Tensor(new_fin)),
                Tensor(flat_parent.astype(jnp.int32)))

    def finished(self, aux):
        return bool(np.asarray(aux[1]._data).all())


def dynamic_decode(decoder, inits=None, max_step_num=32, **kw):
    """reference decoder.py dynamic_decode: run decoder.initialize + step
    until all beams finish or max_step_num; returns (ids [B, K, T],
    final log-probs [B, K])."""
    tokens, states, aux = decoder.initialize(inits)
    K = decoder.beam_size
    ids, parents = [], []
    for t in range(max_step_num):
        tokens, states, aux, parent = decoder.step(t, tokens, states, aux)
        ids.append(np.asarray(tokens._data))
        parents.append(np.asarray(parent._data))
        if decoder.finished(aux):
            break
    T = len(ids)
    BK = ids[0].shape[0]
    B = BK // K
    # backtrack parent pointers to recover aligned sequences
    seqs = np.zeros((T, BK), np.int64)
    cur = np.arange(BK)
    for t in range(T - 1, -1, -1):
        seqs[t] = ids[t][cur]
        cur = parents[t][cur]
    out = seqs.T.reshape(B, K, T)
    lp = np.asarray(aux[0]._data).reshape(B, K)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lp))
