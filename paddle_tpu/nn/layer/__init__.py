from .layers import Layer  # noqa: F401
from .common import *  # noqa: F401,F403
from .container import Sequential, LayerList, ParameterList, LayerDict  # noqa: F401
from .activation import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .rnn import (SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,  # noqa: F401
                  LSTM, GRU)
from .transformer import (MultiHeadAttention, Transformer, TransformerEncoder,  # noqa: F401
                          TransformerEncoderLayer, TransformerDecoder,
                          TransformerDecoderLayer)
from .extras import (PoissonNLLLoss, GaussianNLLLoss, SoftMarginLoss,  # noqa: F401
                     MultiLabelSoftMarginLoss, MultiMarginLoss,
                     TripletMarginWithDistanceLoss, CTCLoss, RNNTLoss,
                     HSigmoidLoss, AdaptiveLogSoftmaxWithLoss, Softmax2D,
                     Unflatten, ParameterDict, ZeroPad1D, ZeroPad3D,
                     LPPool1D, LPPool2D, FractionalMaxPool2D,
                     FractionalMaxPool3D, MaxUnPool3D, BeamSearchDecoder,
                     dynamic_decode)
from .rnn import RNNCellBase  # noqa: F401
