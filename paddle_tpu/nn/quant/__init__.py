"""paddle.nn.quant analog (reference: python/paddle/nn/quant/quantized_linear
.py) — the serving-facing weight-only quantization API surface."""
from ...quantization.weight_only import (weight_quantize, weight_dequantize,
                                         weight_only_linear)
from ...quantization.qat_layers import QuantedLinear, QuantedConv2D

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "QuantedLinear", "QuantedConv2D"]
