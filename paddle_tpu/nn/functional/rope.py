"""Rotary position embedding — first-class op in the reference
(phi/ops/yaml fused_rope; spmd rule phi/infermeta/spmd_rules/fused_rope.cc)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.dispatch import apply_op, unwrap


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0,
                                    max_position=None):
    """Apply RoPE to q/k/v ([B, S, H, D]). Returns (q', k', v') like the
    reference. `max_position` bounds the sin/cos table STATICALLY — required
    when position_ids is traced (jit decode), where a data-dependent table
    size is impossible and an undersized table would gather out-of-bounds
    (jnp fill mode -> NaN)."""
    sin_a, cos_a = unwrap(sin), unwrap(cos)
    pos = unwrap(position_ids) if position_ids is not None else None

    def build(a_dtype, seq_len, head_dim):
        if sin_a is not None:
            s, c = sin_a, cos_a
        else:
            if pos is not None:
                if max_position is not None:
                    seq_len = max(seq_len, int(max_position))
                else:
                    try:                  # decode: table must reach max pos
                        seq_len = max(seq_len, int(pos.max()) + 1)
                    except Exception:
                        # traced position_ids: fall back to the seq-len table
                        # (correct whenever positions < seq_len, i.e. every
                        # training/eval forward); decode callers whose traced
                        # positions exceed seq_len MUST pass max_position or
                        # the gather goes out of bounds (NaN fill)
                        pass
            inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
            t = jnp.arange(seq_len, dtype=jnp.float32)
            freqs = jnp.outer(t, inv)
            emb = jnp.concatenate([freqs, freqs], axis=-1)
            s, c = jnp.sin(emb), jnp.cos(emb)
        s = s.reshape(-1, s.shape[-1])
        c = c.reshape(-1, c.shape[-1])
        if pos is not None:
            s = jnp.take(s, pos.reshape(-1), axis=0).reshape(pos.shape + (s.shape[-1],))
            c = jnp.take(c, pos.reshape(-1), axis=0).reshape(pos.shape + (c.shape[-1],))
            s, c = s[:, :, None, :], c[:, :, None, :]
        else:
            s, c = s[None, :, None, :], c[None, :, None, :]
        return s.astype(jnp.float32), c.astype(jnp.float32)

    def rope_one(a, s, c):
        af = a.astype(jnp.float32)
        if use_neox_rotary_style:
            out = af * c + _rotate_half(af) * s
        else:
            # interleaved (GPT-J) style
            a1 = af[..., 0::2]
            a2 = af[..., 1::2]
            half = a.shape[-1] // 2
            ch, sh = c[..., :half], s[..., :half]
            o1 = a1 * ch - a2 * sh
            o2 = a2 * ch + a1 * sh
            out = jnp.stack([o1, o2], axis=-1).reshape(af.shape)
        return out.astype(a.dtype)

    outs = []
    for t in (q, k, v):
        if t is None:
            outs.append(None)
            continue
        def f(a):
            s, c = build(a.dtype, a.shape[1], a.shape[-1])
            return rope_one(a, s, c)
        outs.append(apply_op("fused_rope", f, t))
    return tuple(outs)


def rotary_embedding_sin_cos(seq_len, head_dim, base=10000.0, dtype=jnp.float32):
    inv = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.sin(emb).astype(dtype), jnp.cos(emb).astype(dtype)
