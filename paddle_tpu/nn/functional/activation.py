"""Activation functionals (reference: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op


def _act(name, jfn):
    def op(x, name_=None, **kw):
        return apply_op(name, (lambda a: jfn(a, **kw)) if kw else jfn, x)
    op.__name__ = name
    return op


relu = _act("relu", jax.nn.relu)
relu6 = _act("relu6", jax.nn.relu6)
relu_ = relu
sigmoid = _act("sigmoid", jax.nn.sigmoid)
tanh = _act("tanh", jnp.tanh)
silu = _act("silu", jax.nn.silu)
swish = silu
mish = _act("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))
softsign = _act("softsign", jax.nn.soft_sign)
tanhshrink = _act("tanhshrink", lambda a: a - jnp.tanh(a))
log_sigmoid = _act("log_sigmoid", jax.nn.log_sigmoid)


def gelu(x, approximate=False, name=None):
    return apply_op("gelu", lambda a: jax.nn.gelu(a, approximate=approximate), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op("leaky_relu", lambda a: jax.nn.leaky_relu(a, negative_slope), x)


def elu(x, alpha=1.0, name=None):
    return apply_op("elu", lambda a: jax.nn.elu(a, alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op("selu", lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), x)


def celu(x, alpha=1.0, name=None):
    return apply_op("celu", lambda a: jax.nn.celu(a, alpha), x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op("hardtanh", lambda a: jnp.clip(a, min, max), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_op("hardsigmoid", lambda a: jnp.clip(a * slope + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return apply_op("hardswish", lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0, x)


def hardshrink(x, threshold=0.5, name=None):
    return apply_op("hardshrink", lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply_op("softshrink",
                    lambda a: jnp.where(a > threshold, a - threshold,
                                        jnp.where(a < -threshold, a + threshold, 0.0)), x)


def softplus(x, beta=1, threshold=20, name=None):
    return apply_op("softplus",
                    lambda a: jnp.where(a * beta > threshold, a,
                                        jax.nn.softplus(a * beta) / beta), x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    dt = convert_dtype(dtype)
    def f(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.softmax(a, axis=axis)
    return apply_op("softmax", f, x)


softmax_ = softmax


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    dt = convert_dtype(dtype)
    def f(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.log_softmax(a, axis=axis)
    return apply_op("log_softmax", f, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core.rng import next_key
    key = next_key()
    def f(a):
        g = -jnp.log(-jnp.log(jax.random.uniform(key, a.shape) + 1e-20) + 1e-20)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False)
            y = y_hard - jax.lax.stop_gradient(y) + y
        return y
    return apply_op("gumbel_softmax", f, x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size == 1:
            return jnp.where(a > 0, a, w.reshape(()) * a)
        ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
        shape = [1] * a.ndim
        shape[ch_axis] = w.size
        return jnp.where(a > 0, a, w.reshape(shape) * a)
    return apply_op("prelu", f, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    from ...core.rng import next_key
    if training:
        key = next_key()
        def f(a):
            slope = jax.random.uniform(key, a.shape, jnp.float32, lower, upper).astype(a.dtype)
            return jnp.where(a >= 0, a, slope * a)
        return apply_op("rrelu", f, x)
    mid = (lower + upper) / 2
    return apply_op("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op("thresholded_relu", lambda a: jnp.where(a > threshold, a, value), x)


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply_op("maxout", f, x)


def glu(x, axis=-1, name=None):
    def f(a):
        a1, a2 = jnp.split(a, 2, axis=axis)
        return a1 * jax.nn.sigmoid(a2)
    return apply_op("glu", f, x)


def swiglu(x, y=None, name=None):
    """LLM gate activation — first-class yaml op in the reference
    (phi/kernels/swiglu_kernel.h)."""
    if y is None:
        def f(a):
            a1, a2 = jnp.split(a, 2, axis=-1)
            return jax.nn.silu(a1) * a2
        return apply_op("swiglu", f, x)
    return apply_op("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)
