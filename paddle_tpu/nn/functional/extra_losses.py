"""Round-2 loss-surface completion (reference: python/paddle/nn/functional/
loss.py — the losses absent after round 1: poisson_nll, multi-label /
multi-margin / soft-margin families, gaussian_nll, dice, log, npair,
hsigmoid, margin_cross_entropy, ctc, rnnt, adaptive log-softmax).

All math in f32 with the file-standard `_reduce` semantics from loss.py.
"""
from __future__ import annotations

import math as _math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op, unwrap
from ...core.tensor import Tensor
from .loss import _reduce

NEG = -1e30


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    """reference loss.py poisson_nll_loss."""
    def f(x, y):
        x32, y32 = x.astype(jnp.float32), y.astype(jnp.float32)
        if log_input:
            out = jnp.exp(x32) - y32 * x32
        else:
            out = x32 - y32 * jnp.log(x32 + epsilon)
        if full:
            # Stirling approximation for y! applied where y > 1
            stir = y32 * jnp.log(y32 + 1e-30) - y32 + 0.5 * jnp.log(
                2 * _math.pi * jnp.maximum(y32, 1e-30))
            out = out + jnp.where(y32 > 1, stir, 0.0)
        return _reduce(out, reduction)
    return apply_op("poisson_nll_loss", f, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """reference loss.py gaussian_nll_loss."""
    def f(mu, y, var):
        v = jnp.maximum(var.astype(jnp.float32), epsilon)
        out = 0.5 * (jnp.log(v) +
                     (y.astype(jnp.float32) - mu.astype(jnp.float32)) ** 2 / v)
        if full:
            out = out + 0.5 * _math.log(2 * _math.pi)
        return _reduce(out, reduction)
    return apply_op("gaussian_nll_loss", f, input, label, variance)


def soft_margin_loss(input, label, reduction="mean", name=None):
    """reference loss.py soft_margin_loss: log(1 + exp(-y * x))."""
    def f(x, y):
        out = jnp.log1p(jnp.exp(-y.astype(jnp.float32) * x.astype(jnp.float32)))
        return _reduce(out, reduction)
    return apply_op("soft_margin_loss", f, input, label)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    """reference loss.py multi_label_soft_margin_loss."""
    args = (input, label) + ((weight,) if weight is not None else ())

    def f(x, y, *w):
        x32, y32 = x.astype(jnp.float32), y.astype(jnp.float32)
        per = -(y32 * jax.nn.log_sigmoid(x32) +
                (1 - y32) * jax.nn.log_sigmoid(-x32))
        if w:
            per = per * w[0].astype(jnp.float32)
        out = per.mean(axis=-1)
        return _reduce(out, reduction)
    return apply_op("multi_label_soft_margin_loss", f, *args)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference loss.py multi_margin_loss (hinge over classes)."""
    lbl = unwrap(label)
    args = (input,) + ((weight,) if weight is not None else ())

    def f(x, *w):
        x32 = x.astype(jnp.float32)
        N, C = x32.shape
        correct = jnp.take_along_axis(x32, lbl[:, None].astype(jnp.int32),
                                      axis=1)
        m = jnp.maximum(margin - correct + x32, 0.0) ** p
        if w:
            m = m * w[0].astype(jnp.float32)[lbl][:, None]
        onehot = jax.nn.one_hot(lbl, C, dtype=jnp.float32)
        out = jnp.sum(m * (1 - onehot), axis=1) / C
        return _reduce(out, reduction)
    return apply_op("multi_margin_loss", f, *args)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    """reference loss.py triplet_margin_with_distance_loss (custom metric)."""
    if distance_function is None:
        def distance_function(a, b):
            diff = a - b
            return (diff * diff).sum(-1).sqrt() if isinstance(diff, Tensor) \
                else jnp.sqrt(jnp.sum(diff * diff, -1) + 1e-12)
    d_ap = distance_function(input, positive)
    d_an = distance_function(input, negative)
    if swap:
        d_pn = distance_function(positive, negative)
        d_an = d_an.minimum(d_pn) if isinstance(d_an, Tensor) else \
            jnp.minimum(d_an, d_pn)

    def f(ap, an):
        out = jnp.maximum(ap.astype(jnp.float32) - an.astype(jnp.float32)
                          + margin, 0.0)
        return _reduce(out, reduction)
    return apply_op("triplet_margin_with_distance_loss", f, d_ap, d_an)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference loss.py dice_loss: input [N, ..., C] probs, label [N, ..., 1]
    class ids."""
    lbl = unwrap(label)

    def f(x):
        x32 = x.astype(jnp.float32)
        C = x32.shape[-1]
        onehot = jax.nn.one_hot(lbl.squeeze(-1), C, dtype=jnp.float32)
        red = tuple(range(1, x32.ndim))
        inter = jnp.sum(x32 * onehot, axis=red)
        union = jnp.sum(x32, axis=red) + jnp.sum(onehot, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return apply_op("dice_loss", f, input)


def log_loss(input, label, epsilon=1e-4, name=None):
    """reference loss.py log_loss (binary cross entropy on probabilities,
    elementwise, no reduction)."""
    def f(p, y):
        p32, y32 = p.astype(jnp.float32), y.astype(jnp.float32)
        return -(y32 * jnp.log(p32 + epsilon) +
                 (1 - y32) * jnp.log(1 - p32 + epsilon))
    return apply_op("log_loss", f, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference loss.py npair_loss."""
    lbl = unwrap(labels)

    def f(a, p):
        a32, p32 = a.astype(jnp.float32), p.astype(jnp.float32)
        reg = l2_reg * (jnp.mean(jnp.sum(a32 * a32, 1)) +
                        jnp.mean(jnp.sum(p32 * p32, 1))) * 0.25
        sim = a32 @ p32.T                       # [N, N]
        same = (lbl[:, None] == lbl[None, :]).astype(jnp.float32)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=1)
        xent = -jnp.mean(jnp.sum(tgt * logp, axis=1))
        return xent + reg
    return apply_op("npair_loss", f, anchor, positive)


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    """Hierarchical sigmoid over the default complete binary tree (reference
    loss.py hsigmoid_loss; phi hsigmoid_loss kernel). Without a custom
    path_table, class c's path is its binary-heap route: internal node ids
    are (c + num_classes) halved until the root, codes are the low bits."""
    lbl = np.asarray(unwrap(label))
    depth = int(np.ceil(np.log2(max(num_classes, 2))))
    # precompute per-sample paths on host (labels are data, shapes static)
    if path_table is not None:
        table = np.asarray(unwrap(path_table))
        codes = np.asarray(unwrap(path_code)).astype(np.float32)
        valid = (table >= 0).astype(np.float32)
        table = np.maximum(table, 0)
    else:
        table = np.zeros((len(lbl), depth), np.int64)
        codes = np.zeros((len(lbl), depth), np.float32)
        valid = np.zeros((len(lbl), depth), np.float32)
        for i, c in enumerate(lbl.reshape(-1)):
            node = int(c) + num_classes
            k = 0
            while node > 1:
                table[i, k] = node // 2 - 1     # internal node row in weight
                codes[i, k] = node % 2
                valid[i, k] = 1.0
                node //= 2
                k += 1
    tj, cj, vj = jnp.asarray(table), jnp.asarray(codes), jnp.asarray(valid)
    args = (input, weight) + ((bias,) if bias is not None else ())

    def f(x, w, *b):
        x32 = x.astype(jnp.float32)
        wsel = w.astype(jnp.float32)[tj]         # [N, depth, D]
        logits = jnp.einsum("nd,nkd->nk", x32, wsel)
        if b:
            logits = logits + b[0].astype(jnp.float32).reshape(-1)[tj]
        # code 1 -> sigmoid(logit), code 0 -> sigmoid(-logit)
        sign = 2 * cj - 1
        logp = jax.nn.log_sigmoid(sign * logits) * vj
        return -jnp.sum(logp, axis=1, keepdims=True)
    return apply_op("hsigmoid_loss", f, *args)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean", name=None):
    """ArcFace-family margin softmax (reference loss.py margin_cross_entropy:
    cos(m1*theta + m2) - m3 applied to the target logit)."""
    lbl = unwrap(label)

    def f(lg):
        # clip strictly inside (-1, 1): d/dx arccos explodes at the boundary
        # and jnp.where/clip would propagate NaN grads for exact +-1 logits
        x = jnp.clip(lg.astype(jnp.float32), -1.0 + 1e-6, 1.0 - 1e-6)
        N, C = x.shape
        theta = jnp.arccos(jnp.take_along_axis(
            x, lbl[:, None].astype(jnp.int32), axis=1))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(lbl, C, dtype=jnp.float32)
        adj = x * (1 - onehot) + target * onehot
        adj = adj * scale
        logp = jax.nn.log_softmax(adj, axis=1)
        loss = -jnp.take_along_axis(logp, lbl[:, None].astype(jnp.int32),
                                    axis=1)
        sm = jnp.exp(logp)
        red = _reduce(loss, reduction)
        return (red, sm) if return_softmax else red
    out = apply_op("margin_cross_entropy", f, logits)
    return out


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """CTC forward-algorithm loss (reference loss.py ctc_loss over the
    warpctc kernel). log_probs [T, B, C] raw logits (log-softmax applied
    here, matching the reference), labels [B, L] padded with anything.
    lax.scan over time; log-domain alpha recursion over the extended
    blank-interleaved label sequence."""
    lbl = unwrap(labels)
    in_len = unwrap(input_lengths)
    lab_len = unwrap(label_lengths)

    def f(lp):
        x = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)  # [T, B, C]
        T, B, C = x.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended sequence: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        same_as_prev2 = jnp.concatenate(
            [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        alpha0 = jnp.full((B, S), NEG)
        alpha0 = alpha0.at[:, 0].set(x[0, jnp.arange(B), ext[:, 0]])
        alpha0 = alpha0.at[:, 1].set(jnp.where(
            lab_len > 0, x[0, jnp.arange(B), ext[:, 1]], NEG))

        def step(alpha, xt):
            em = xt[jnp.arange(B)[:, None], ext]          # [B, S]
            stay = alpha
            prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
            prev2 = jnp.where(
                same_as_prev2, NEG,
                jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1))
            # blanks (even s) can't skip
            even = (jnp.arange(S) % 2 == 0)[None, :]
            prev2 = jnp.where(even, NEG, prev2)
            new = jnp.logaddexp(jnp.logaddexp(stay, prev1), prev2) + em
            return new, new

        _, alphas = jax.lax.scan(step, alpha0, x[1:])
        alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, S]
        # per-sample final time index and final states (2*len-1, 2*len)
        t_idx = jnp.clip(in_len.astype(jnp.int32) - 1, 0, T - 1)
        last = alphas[t_idx, jnp.arange(B)]               # [B, S]
        s1 = jnp.clip(2 * lab_len.astype(jnp.int32) - 1, 0, S - 1)
        s2 = jnp.clip(2 * lab_len.astype(jnp.int32), 0, S - 1)
        a1 = jnp.take_along_axis(last, s1[:, None], 1)[:, 0]
        a2 = jnp.take_along_axis(last, s2[:, None], 1)[:, 0]
        # empty target: only the all-blank state exists (s1 would alias s2
        # and double-count it)
        ll = jnp.where(lab_len > 0, jnp.logaddexp(a1, a2), a2)
        loss = -ll
        if norm_by_times:
            loss = loss / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        if reduction == "mean":
            # reference/torch semantics: mean of loss / label_length
            return jnp.mean(loss / jnp.maximum(
                lab_len.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)
    return apply_op("ctc_loss", f, log_probs)


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-T transducer loss (reference loss.py rnnt_loss over warprnnt).
    input [B, T, U+1, C] joint-network logits; alpha DP over the (T, U) grid
    (scan over t, inner scan over u) in log domain."""
    lbl = unwrap(label)
    in_len = unwrap(input_lengths)
    lab_len = unwrap(label_lengths)

    def f(lg):
        x = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)  # [B,T,U1,C]
        B, T, U1, C = x.shape
        U = U1 - 1
        bi = jnp.arange(B)
        blank_lp = x[..., blank]                                  # [B, T, U+1]
        if U > 0:
            idx = lbl[:, :U].astype(jnp.int32)                    # [B, U]
            y_lp = jnp.take_along_axis(
                x[:, :, :U, :], idx[:, None, :, None], axis=3)[..., 0]
            if fastemit_lambda:
                # FastEmit (torchaudio semantics): boost label-emission
                # GRADIENTS by (1 + lambda); the loss VALUE is unchanged
                y_lp = (1.0 + fastemit_lambda) * y_lp \
                    - fastemit_lambda * jax.lax.stop_gradient(y_lp)
        else:
            y_lp = jnp.zeros((B, T, 0))                           # [B, T, U]

        def label_sweep(from_blank, y_row):
            """Fill one alpha row: u-sequential label moves folded against
            the per-u blank arrivals (lax.scan over u)."""
            a0 = from_blank[:, 0]
            if U == 0:
                return a0[:, None]

            def u_body(carry, u):
                lbl_move = carry + y_row[:, u - 1]
                cur = jnp.logaddexp(from_blank[:, u], lbl_move)
                return cur, cur
            _, rest = jax.lax.scan(u_body, a0, jnp.arange(1, U1))
            return jnp.concatenate([a0[:, None], rest.T], axis=1)

        # t = 0 row: no blank arrivals except the (0,0) origin
        neg_row = jnp.full((B, U1), NEG).at[:, 0].set(0.0)
        alpha0 = label_sweep(neg_row, y_lp[:, 0])

        def t_step(alpha_prev, t):
            from_blank = alpha_prev + blank_lp[:, t - 1]          # [B, U+1]
            alpha_t = label_sweep(from_blank, y_lp[:, t])
            return alpha_t, alpha_t

        _, rest_alpha = jax.lax.scan(t_step, alpha0, jnp.arange(1, T))
        all_alpha = jnp.concatenate([alpha0[None], rest_alpha], axis=0)  # [T,B,U+1]

        t_idx = jnp.clip(in_len.astype(jnp.int32) - 1, 0, T - 1)
        u_idx = jnp.clip(lab_len.astype(jnp.int32), 0, U1 - 1)
        final = all_alpha[t_idx, bi, u_idx] + blank_lp[bi, t_idx, u_idx]
        loss = -final
        if reduction == "mean":
            return jnp.mean(loss)
        return _reduce(loss, reduction)
    return apply_op("rnnt_loss", f, input)


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """reference loss.py adaptive_log_softmax_with_loss (torch-style
    adaptive softmax): head covers [0, cutoffs[0]) + one logit per tail
    cluster; each tail projects down then classifies within its range.
    Returns (per-sample logprob-of-target, mean NLL loss)."""
    lbl = unwrap(label)
    cuts = list(cutoffs)
    args = [input, head_weight] + list(tail_weights or []) \
        + ([head_bias] if head_bias is not None else [])
    n_tail_arrays = len(tail_weights or [])

    def f(x, hw, *rest):
        tails = rest[:n_tail_arrays]
        hb = rest[n_tail_arrays:] if head_bias is not None else ()
        x32 = x.astype(jnp.float32)
        head = x32 @ hw.astype(jnp.float32)     # head_weight is [in, out]
        if hb:
            head = head + hb[0].astype(jnp.float32)
        head_lp = jax.nn.log_softmax(head, axis=1)
        shortlist = cuts[0]
        out = jnp.take_along_axis(
            head_lp, jnp.clip(lbl, 0, shortlist - 1)[:, None].astype(jnp.int32),
            axis=1)[:, 0]
        in_short = lbl < shortlist
        result = jnp.where(in_short, out, 0.0)
        for ci in range(len(tails) // 2):
            lo = cuts[ci]
            hi = cuts[ci + 1]
            proj, cls = tails[2 * ci], tails[2 * ci + 1]
            h = x32 @ proj.astype(jnp.float32)
            tail_logits = h @ cls.astype(jnp.float32)
            tail_lp = jax.nn.log_softmax(tail_logits, axis=1)
            cluster_lp = head_lp[:, shortlist + ci]
            rel = jnp.clip(lbl - lo, 0, hi - lo - 1)
            lp = cluster_lp + jnp.take_along_axis(
                tail_lp, rel[:, None].astype(jnp.int32), axis=1)[:, 0]
            mask = (lbl >= lo) & (lbl < hi)
            result = jnp.where(mask, lp, result)
        return result, -jnp.mean(result)
    return apply_op("adaptive_log_softmax_with_loss", f, *args)
