"""Normalization functionals (reference: python/paddle/nn/functional/norm.py;
rms_norm is a first-class yaml op in the reference: phi/kernels/rms_norm_kernel.h).

All stats accumulate in float32 regardless of input dtype (bf16-first contract)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op, unwrap
from ...core.tensor import Tensor


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else [normalized_shape]
    n_axes = len(ns)
    def f(a, *wb):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(af - mean), axis=axes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("layer_norm", f, *args)


def rms_norm(x, weight=None, bias=None, epsilon=1e-6, begin_norm_axis=-1, name=None):
    def f(a, *wb):
        af = a.astype(jnp.float32)
        ax = begin_norm_axis if begin_norm_axis >= 0 else a.ndim + begin_norm_axis
        axes = tuple(range(ax, a.ndim))
        ms = jnp.mean(jnp.square(af), axis=axes, keepdims=True)
        out = af * jax.lax.rsqrt(ms + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("rms_norm", f, *args)


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               name=None):
    use_stats = (not training) if use_global_stats is None else use_global_stats
    ch_axis = 1 if data_format.startswith("NC") else -1
    def f(a, *wb):
        nd = a.ndim
        cax = ch_axis % nd
        red_axes = tuple(i for i in range(nd) if i != cax)
        af = a.astype(jnp.float32)
        if use_stats:
            mean = unwrap(running_mean).astype(jnp.float32)
            var = unwrap(running_var).astype(jnp.float32)
        else:
            mean = jnp.mean(af, axis=red_axes)
            var = jnp.var(af, axis=red_axes)
        shape = [1] * nd
        shape[cax] = a.shape[cax]
        out = (af - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        # return batch stats alongside so the running update reuses this reduction
        return out.astype(a.dtype), mean, var
    args = [x] + [t for t in (weight, bias) if t is not None]
    out, bmean, bvar = apply_op("batch_norm", f, *args)
    if training and not use_stats:
        rm, rv = running_mean, running_var
        rm._data = (momentum * unwrap(rm).astype(jnp.float32)
                    + (1 - momentum) * unwrap(bmean)).astype(rm._data.dtype)
        rv._data = (momentum * unwrap(rv).astype(jnp.float32)
                    + (1 - momentum) * unwrap(bvar)).astype(rv._data.dtype)
    return out


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None, data_format="NCHW",
               name=None):
    def f(a, *wb):
        chan_last = not data_format.startswith("NC")
        if chan_last:
            a_ = jnp.moveaxis(a, -1, 1)
        else:
            a_ = a
        n, c = a_.shape[0], a_.shape[1]
        spatial = a_.shape[2:]
        af = a_.astype(jnp.float32).reshape(n, num_groups, c // num_groups, *spatial)
        axes = tuple(range(2, af.ndim))
        mean = jnp.mean(af, axis=axes, keepdims=True)
        var = jnp.var(af, axis=axes, keepdims=True)
        out = ((af - mean) * jax.lax.rsqrt(var + epsilon)).reshape(n, c, *spatial)
        shape = [1] * out.ndim
        shape[1] = c
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        out = out.astype(a.dtype)
        return jnp.moveaxis(out, 1, -1) if chan_last else out
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("group_norm", f, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5, data_format="NCHW",
                  name=None):
    def f(a, *wb):
        nd = a.ndim
        cax = 1 if data_format.startswith("NC") else nd - 1
        red_axes = tuple(i for i in range(2, nd)) if cax == 1 else tuple(range(1, nd - 1))
        af = a.astype(jnp.float32)
        mean = jnp.mean(af, axis=red_axes, keepdims=True)
        var = jnp.var(af, axis=red_axes, keepdims=True)
        out = (af - mean) * jax.lax.rsqrt(var + eps)
        shape = [1] * nd
        shape[cax] = a.shape[cax]
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32).reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32).reshape(shape)
        return out.astype(a.dtype)
    args = [x] + [t for t in (weight, bias) if t is not None]
    return apply_op("instance_norm", f, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                        name=None):
    def f(a):
        cax = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = jnp.square(a.astype(jnp.float32))
        c = a.shape[cax]
        half = size // 2
        pads = [(0, 0)] * a.ndim
        pads[cax] = (half, size - half - 1)
        sq_p = jnp.pad(sq, pads)
        acc = jnp.zeros_like(sq)
        for i in range(size):
            sl = [slice(None)] * a.ndim
            sl[cax] = slice(i, i + c)
            acc = acc + sq_p[tuple(sl)]
        return (a.astype(jnp.float32) / jnp.power(k + alpha * acc, beta)).astype(a.dtype)
    return apply_op("local_response_norm", f, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(a.astype(jnp.float32)), p),
                                axis=axis, keepdims=True), 1.0 / p)
        return (a.astype(jnp.float32) / jnp.maximum(nrm, epsilon)).astype(a.dtype)
    return apply_op("normalize", f, x)
