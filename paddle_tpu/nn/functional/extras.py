"""Round-2 functional-surface completion, part 2 (reference:
python/paddle/nn/functional/ — pooling variants, vision sampling, seq2seq
helpers, attention wrappers, inplace activation forms).
"""
from __future__ import annotations

import math as _math

import numpy as np
import jax
import jax.numpy as jnp

from ...core.dispatch import apply_op, unwrap
from ...core.tensor import Tensor
from . import activation as _act
from .pooling import avg_pool1d, avg_pool2d, max_unpool2d


# ---- inplace activation forms (reference: elu_/tanh_/... in activation.py) --
def _inplace(fn):
    def f(x, *a, **k):
        out = fn(x, *a, **k)
        x._data = out._data
        x._grad_node, x._out_slot = out._grad_node, out._out_slot
        if not out.stop_gradient:
            x.stop_gradient = False
        return x
    return f


elu_ = _inplace(_act.elu)
hardtanh_ = _inplace(_act.hardtanh)
leaky_relu_ = _inplace(_act.leaky_relu)
tanh_ = _inplace(_act.tanh)
thresholded_relu_ = _inplace(_act.thresholded_relu)


# ---- distance ----------------------------------------------------------------
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """reference distance.py pairwise_distance (the PairwiseDistance layer's
    functional form)."""
    def f(a, b):
        d = (a - b).astype(jnp.float32) + epsilon
        out = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
        return out[..., None] if keepdim else out
    return apply_op("pairwise_distance", f, x, y)


# ---- LP / fractional pooling -------------------------------------------------
def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    """reference pooling.py lp_pool1d: (avg(|x|^p) * k)^(1/p)."""
    p = float(norm_type)
    k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
    powed = apply_op("lp_pow", lambda a: jnp.abs(a.astype(jnp.float32)) ** p, x)
    pooled = avg_pool1d(powed, kernel_size, stride, padding,
                        ceil_mode=ceil_mode, data_format=data_format,
                        exclusive=False)
    return apply_op("lp_root",
                    lambda a: (a * float(k)) ** (1.0 / p), pooled)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    powed = apply_op("lp_pow", lambda a: jnp.abs(a.astype(jnp.float32)) ** p, x)
    pooled = avg_pool2d(powed, kernel_size, stride, padding,
                        ceil_mode=ceil_mode, data_format=data_format,
                        exclusive=False)
    n = float(np.prod(ks))
    return apply_op("lp_root", lambda a: (a * n) ** (1.0 / p), pooled)


def _fractional_bounds(in_size, out_size, u):
    """Pseudo-random fractional pooling boundaries (torch-style: alpha =
    in/out; start_i = ceil(alpha*(i+u)) - ceil(alpha*u))."""
    alpha = in_size / out_size
    i = np.arange(out_size + 1)
    pts = np.ceil(alpha * (i + u)).astype(np.int64) - int(np.ceil(alpha * u))
    pts = np.clip(pts, 0, in_size)
    pts[-1] = in_size
    return pts


def _frac_window(bounds, i, k, limit):
    """[start, end) of fractional window i: pseudo-random partition cell, or
    an overlapping k-sized window at the cell's start when kernel_size set."""
    lo = bounds[i]
    hi = bounds[i + 1] if k is None else min(lo + k, limit)
    return lo, max(hi, lo + 1)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference pooling.py fractional_max_pool2d (NCHW)."""
    from ...core.rng import next_key
    N, C, H, W = x.shape
    oh, ow = (output_size, output_size) if isinstance(output_size, int) \
        else tuple(output_size)
    if random_u is None:
        key = next_key()
        u = float(jax.random.uniform(key, (), minval=0.05, maxval=0.95))
    else:
        u = float(random_u)
    hb = _fractional_bounds(H, oh, u)
    wb = _fractional_bounds(W, ow, u)
    ks = None if kernel_size is None else (
        (kernel_size, kernel_size) if isinstance(kernel_size, int)
        else tuple(kernel_size))
    kh, kw = (None, None) if ks is None else ks

    def f(a):
        rows = []
        for i in range(oh):
            cols = []
            for j in range(ow):
                h0, h1 = _frac_window(hb, i, kh, H)
                w0, w1 = _frac_window(wb, j, kw, W)
                cols.append(jnp.max(a[:, :, h0:h1, w0:w1], axis=(2, 3)))
            rows.append(jnp.stack(cols, axis=-1))
        return jnp.stack(rows, axis=-2)     # [N, C, oh, ow]
    out = apply_op("fractional_max_pool2d", f, x)
    if return_mask:
        # indices of the max inside each fractional window (flat H*W)
        a_np = np.asarray(unwrap(x))
        m = np.zeros((N, C, oh, ow), np.int32)
        for i in range(oh):
            for j in range(ow):
                h0, h1 = _frac_window(hb, i, kh, H)
                w0, w1 = _frac_window(wb, j, kw, W)
                win = a_np[:, :, h0:h1, w0:w1]
                k = np.argmax(win.reshape(N, C, -1), axis=-1)
                ww = win.shape[3]
                m[:, :, i, j] = ((h0 + k // ww) * W + (w0 + k % ww))
        return out, Tensor(jnp.asarray(m))
    return out


def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference pooling.py fractional_max_pool3d (NCDHW)."""
    if return_mask:
        raise NotImplementedError("fractional_max_pool3d return_mask")
    from ...core.rng import next_key
    N, C, D, H, W = x.shape
    od, oh, ow = (output_size,) * 3 if isinstance(output_size, int) \
        else tuple(output_size)
    if random_u is None:
        u = float(jax.random.uniform(next_key(), (), minval=0.05, maxval=0.95))
    else:
        u = float(random_u)
    db = _fractional_bounds(D, od, u)
    hb = _fractional_bounds(H, oh, u)
    wb = _fractional_bounds(W, ow, u)

    ks = None if kernel_size is None else (
        (kernel_size,) * 3 if isinstance(kernel_size, int)
        else tuple(kernel_size))

    def f(a):
        out = jnp.zeros(a.shape[:2] + (od, oh, ow), a.dtype)
        for d in range(od):
            for i in range(oh):
                for j in range(ow):
                    d0, d1 = _frac_window(db, d, None if ks is None else ks[0], D)
                    h0, h1 = _frac_window(hb, i, None if ks is None else ks[1], H)
                    w0, w1 = _frac_window(wb, j, None if ks is None else ks[2], W)
                    out = out.at[:, :, d, i, j].set(
                        jnp.max(a[:, :, d0:d1, h0:h1, w0:w1], axis=(2, 3, 4)))
        return out
    return apply_op("fractional_max_pool3d", f, x)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    """Inverse of a max_pool3d-with-indices (flat D*H*W positions)."""
    if data_format != "NCDHW":
        raise ValueError("max_unpool3d supports NCDHW only")
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else tuple(kernel_size)
    st = ks if stride is None else ((stride,) * 3 if isinstance(stride, int)
                                    else tuple(stride))
    pd = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    if output_size is None:
        sp = x.shape[2:]
        output_size = tuple((sp[i] - 1) * st[i] - 2 * pd[i] + ks[i]
                            for i in range(3))
    Do, Ho, Wo = tuple(output_size)[-3:]

    def f(a, idx):
        N, C = a.shape[:2]
        flat = jnp.zeros((N, C, Do * Ho * Wo), a.dtype)
        ii = jnp.arange(N)[:, None, None]
        cc = jnp.arange(C)[None, :, None]
        out = flat.at[ii, cc, idx.reshape(N, C, -1)].set(a.reshape(N, C, -1))
        return out.reshape(N, C, Do, Ho, Wo)
    return apply_op("max_unpool3d", f, x, indices)


# ---- vision sampling ---------------------------------------------------------
def affine_grid(theta, out_shape, align_corners=True, name=None):
    """reference vision.py affine_grid: theta [N, 2, 3] -> grid [N, H, W, 2]
    (the 5-element NCDHW/theta [N, 3, 4] volumetric form is not implemented)."""
    if len(out_shape) == 5:
        raise NotImplementedError("3-D affine_grid (NCDHW out_shape)")
    N, _, H, W = (out_shape if len(out_shape) == 4 else
                  (out_shape[0], 1, out_shape[1], out_shape[2]))

    def f(th):
        t32 = th.astype(jnp.float32)
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, W)
            ys = jnp.linspace(-1.0, 1.0, H)
        else:
            xs = (jnp.arange(W) * 2 + 1) / W - 1
            ys = (jnp.arange(H) * 2 + 1) / H - 1
        gx, gy = jnp.meshgrid(xs, ys)                    # [H, W]
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)        # [H, W, 3]
        return jnp.einsum("hwk,njk->nhwj", base, t32)    # [N, H, W, 2]
    return apply_op("affine_grid", f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """reference vision.py grid_sample (NCHW + grid [N, Ho, Wo, 2] in
    [-1, 1] xy order). Bilinear/nearest; zeros/border/reflection padding."""
    def f(a, g):
        a32 = a.astype(jnp.float32)
        N, C, H, W = a32.shape
        gx, gy = g[..., 0].astype(jnp.float32), g[..., 1].astype(jnp.float32)
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2

        def reflect(v, lo, hi):
            rng = hi - lo
            if rng <= 0:
                return v
            t = jnp.mod(v - lo, 2 * rng)
            return lo + (rng - jnp.abs(t - rng))   # triangle-wave fold
        if padding_mode == "reflection":
            if align_corners:
                fx = reflect(fx, 0.0, W - 1.0)
                fy = reflect(fy, 0.0, H - 1.0)
            else:
                # torch convention: reflect about pixel EDGES, then clip
                fx = jnp.clip(reflect(fx, -0.5, W - 0.5), 0, W - 1)
                fy = jnp.clip(reflect(fy, -0.5, H - 0.5), 0, H - 1)

        def sample(ix, iy):
            okx = (ix >= 0) & (ix <= W - 1)
            oky = (iy >= 0) & (iy <= H - 1)
            cx = jnp.clip(ix, 0, W - 1).astype(jnp.int32)
            cy = jnp.clip(iy, 0, H - 1).astype(jnp.int32)
            v = a32[jnp.arange(N)[:, None, None], :, cy, cx]  # [N,Ho,Wo,C]
            if padding_mode == "zeros":
                v = v * (okx & oky)[..., None]
            return v

        if mode == "nearest":
            out = sample(jnp.round(fx), jnp.round(fy))
        else:
            x0, y0 = jnp.floor(fx), jnp.floor(fy)
            x1, y1 = x0 + 1, y0 + 1
            wa = (x1 - fx) * (y1 - fy)
            wb = (fx - x0) * (y1 - fy)
            wc = (x1 - fx) * (fy - y0)
            wd = (fx - x0) * (fy - y0)
            out = (sample(x0, y0) * wa[..., None] + sample(x1, y0) * wb[..., None]
                   + sample(x0, y1) * wc[..., None] + sample(x1, y1) * wd[..., None])
        return jnp.moveaxis(out, -1, 1).astype(a.dtype)   # [N, C, Ho, Wo]
    return apply_op("grid_sample", f, x, grid)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """reference extension.py temporal_shift (TSM): shift 1/r channels one
    frame back, 1/r forward within each segment."""
    if data_format != "NCHW":
        raise ValueError("temporal_shift supports NCHW")

    def f(a):
        NT, C, H, W = a.shape
        N = NT // seg_num
        v = a.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        back = jnp.concatenate([v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], 1)
        fwd = jnp.concatenate([jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], 1)
        keep = v[:, :, c2:]
        return jnp.concatenate([back, fwd, keep], axis=2).reshape(NT, C, H, W)
    return apply_op("temporal_shift", f, x)


def gather_tree(ids, parents, name=None):
    """reference extension.py gather_tree: backtrack beam-search parent
    pointers [T, B, beam] -> full sequences."""
    def f(idv, par):
        T = idv.shape[0]

        def step(next_beam, t):
            # next_beam: [B, beam] beam index selected at t+1
            cur_parent = jnp.take_along_axis(par[t], next_beam, axis=1)
            tok = jnp.take_along_axis(idv[t], next_beam, axis=1)
            return cur_parent, tok
        init = jnp.broadcast_to(jnp.arange(idv.shape[2])[None, :],
                                idv.shape[1:]).astype(idv.dtype)
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return toks[::-1]
    return apply_op("gather_tree", f, ids, parents)


def class_center_sample(label, num_classes, num_samples, group=None,
                        name=None):
    """reference common.py class_center_sample: keep all positive classes +
    uniformly sampled negatives; remap labels into the sampled index space."""
    from ...core.rng import next_key
    lbl = np.asarray(unwrap(label)).reshape(-1)
    pos = np.unique(lbl)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        neg_pool = np.setdiff1d(np.arange(num_classes), pos)
        k = num_samples - len(pos)
        key = next_key()
        pick = np.asarray(jax.random.choice(
            key, len(neg_pool), (k,), replace=False))
        sampled = np.sort(np.concatenate([pos, neg_pool[pick]]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lbl])),
            Tensor(jnp.asarray(sampled.astype(np.int64))))


# ---- attention wrappers ------------------------------------------------------
def flashmask_attention(query, key, value, startend_row_indices=None,
                        dropout=0.0, causal=False, name=None, **kw):
    """reference flash_attention.py flashmask_attention: flash attention with
    sparse row-bound masks. Realized via the dense-mask SDPA path (XLA fuses);
    the row-bound form maps to an explicit boolean mask."""
    from .attention import scaled_dot_product_attention
    mask = None
    if startend_row_indices is not None:
        idx = unwrap(startend_row_indices)          # [B, H, S, 1] (causal LT)
        S = query.shape[1]
        rows = jnp.arange(S)
        start = jnp.squeeze(idx, -1)                # [B, Hm, S]
        # token j is masked for query i when i >= start[j]
        m = rows[None, None, :, None] < start[:, :, None, :]
        mask = Tensor(jnp.where(m, 0.0, -jnp.inf).astype(jnp.float32))
    return scaled_dot_product_attention(query, key, value, attn_mask=mask,
                                        dropout_p=dropout, is_causal=causal)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False, return_softmax=False,
                         *a, **kw):
    """reference flash_attention.py flash_attn_qkvpacked: qkv [B, S, 3, H, D]."""
    from .attention import flash_attention
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    return flash_attention(q, k, v, dropout=dropout, causal=causal,
                           return_softmax=return_softmax)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q=None, cu_seqlens_k=None,
                                *a, **kw):
    raise NotImplementedError(
        "varlen packed flash attention: pad to dense [B, S, 3, H, D] and use "
        "flash_attn_qkvpacked (ragged batching lands with the paged-attention "
        "serving path)")


def sparse_attention(*a, **kw):
    raise NotImplementedError(
        "block-sparse attention is CUDA-only in the reference (sparse_attention "
        "op); on TPU use flashmask_attention for masked patterns")
